"""graftlint tests: per-rule positive/negative fixtures, the CLI JSON
contract, baseline round-trip + fingerprint invalidation, the runtime
sanitizer's RecompileMonitor (ISSUE 4 acceptance: each rule must catch
its seeded violation), and — ISSUE 15 — the interprocedural call-graph
pass: the three audited blind-spot regressions (transitive host sync,
cross-module donation-after-use, distant static_argnums) each as a
positive/negative pair, GL011 cross-module key reuse, the call-graph
edge cases (import cycles, partial chains, self methods, re-exports,
decorated helpers), the content-hash cache, and the --format github /
--changed CI surfaces."""

import json
import textwrap

import pytest

from distributed_pipeline_tpu.analysis import (
    AnalysisCache,
    Baseline,
    all_rules,
    run_paths,
)
from distributed_pipeline_tpu.analysis.cli import main as cli_main


def lint(tmp_path, src, name="snippet.py"):
    p = tmp_path / name
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(src))
    findings, _ = run_paths([str(p)])
    return findings


def lint_files(tmp_path, files):
    """Whole-program lint over a dict of {relpath: source} (the
    interprocedural fixtures need several modules in one pass)."""
    for name, src in files.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    findings, _ = run_paths([str(tmp_path)])
    return findings


def codes(findings):
    return {f.rule for f in findings}


# ------------------------------------------------------------ rule catalog


def test_catalog_has_all_rules():
    got = {r.code for r in all_rules()}
    for expected in ("GL001-key-reuse", "GL002-host-sync",
                     "GL003-donation-after-use", "GL004-impure-jit",
                     "GL005-recompile-hazard", "GL006-raw-shard-map",
                     "GL007-host-sync-in-loop",
                     "GL008-hand-wired-sharding",
                     "GL009-ad-hoc-timing",
                     "GL010-unattributed-flops",
                     "GL011-cross-module-key-reuse",
                     "GL012-stray-pallas-call"):
        assert expected in got


# ------------------------------------------------------------------- GL001


def test_key_reuse_two_consumers(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def f(rng):
            a = jax.random.normal(rng, (2,))
            b = jax.random.uniform(rng, (2,))
            return a + b
    """)
    assert "GL001-key-reuse" in codes(fs)


def test_key_reuse_after_split(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def f(rng):
            keys = jax.random.split(rng, 3)
            c = jax.random.normal(rng, (2,))
            return keys, c
    """)
    assert "GL001-key-reuse" in codes(fs)


def test_key_reuse_in_loop_without_rebinding(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def f(key):
            outs = []
            for i in range(4):
                outs.append(jax.random.normal(key, (2,)))
            return outs
    """)
    assert "GL001-key-reuse" in codes(fs)


def test_key_split_and_fold_in_are_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def f(rng):
            k1, k2 = jax.random.split(rng)
            a = jax.random.normal(k1, (2,))
            b = jax.random.uniform(k2, (2,))
            return a + b
        def g(key):
            outs = []
            for i in range(4):
                k = jax.random.fold_in(key, i)
                outs.append(jax.random.normal(k, (2,)))
            return outs
    """)
    assert "GL001-key-reuse" not in codes(fs)


def test_sampler_output_is_not_a_key(tmp_path):
    # x = normal(key) produces DATA; using x twice is not key reuse
    fs = lint(tmp_path, """
        import jax
        def f(key):
            x = jax.random.normal(key, (2,))
            a = x + 1
            for _ in range(3):
                a = a + x
            return a
    """)
    assert "GL001-key-reuse" not in codes(fs)


def test_key_use_in_one_branch_only_is_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def f(rng, fast):
            if fast:
                return jax.random.normal(rng, (2,))
            return jax.random.uniform(rng, (2,))
    """)
    assert "GL001-key-reuse" not in codes(fs)


# ------------------------------------------------------------------- GL002


def test_host_sync_inside_jit(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import numpy as np
        @jax.jit
        def step(x):
            v = float(x.sum())
            y = np.asarray(x)
            return x * v + y + x.sum().item()
    """)
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) >= 3  # float(), np.asarray, .item()


def test_host_sync_outside_trace_is_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        import numpy as np
        def eager(x):
            return float(np.asarray(x).sum())
    """)
    assert "GL002-host-sync" not in codes(fs)


def test_static_numpy_builders_allowed_under_trace(tmp_path):
    # np.arange/linspace on static python ints is the respaced-timestep
    # idiom (models/sampling.py) — must not be flagged
    fs = lint(tmp_path, """
        import jax
        import numpy as np
        @jax.jit
        def step(x):
            ts = np.arange(10)
            return x + ts.shape[0]
    """)
    assert "GL002-host-sync" not in codes(fs)


def test_host_sync_in_scan_body(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def outer(xs):
            def body(carry, x):
                return carry + float(x), x
            return jax.lax.scan(body, 0.0, xs)
    """)
    assert "GL002-host-sync" in codes(fs)


# ------------------------------------------------------------------- GL003


def test_donation_read_after_call(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from functools import partial
        @partial(jax.jit, donate_argnums=(0,))
        def train(state, batch):
            return state + batch
        def run(state, batch):
            new = train(state, batch)
            stale = state + 1
            return new, stale
    """)
    assert "GL003-donation-after-use" in codes(fs)


def test_donation_with_rebinding_is_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def make(f):
            return jax.jit(f, donate_argnums=(0,))
        step = jax.jit(lambda s, b: s + b, donate_argnums=(0,))
        def run(state, batch):
            state = step(state, batch)
            return state + 1
    """)
    assert "GL003-donation-after-use" not in codes(fs)


def test_donation_through_wrapper_binding(tmp_path):
    # the trainer idiom: AOTStep(jax.jit(f, donate_argnums=...)) bound to
    # an attribute, then the donated attribute read after the call
    fs = lint(tmp_path, """
        import jax
        class Wrap:
            def __init__(self, fn):
                self.fn = fn
        step = Wrap(jax.jit(lambda s, b: s + b, donate_argnums=(0,)))
        def run(holder, batch):
            out = step(holder.state, batch)
            leak = holder.state
            return out, leak
    """)
    assert "GL003-donation-after-use" in codes(fs)


# ------------------------------------------------------------------- GL004


def test_impure_print_and_attr_mutation(tmp_path):
    fs = lint(tmp_path, """
        import jax
        cfg = {}
        class Box:
            pass
        box = Box()
        @jax.jit
        def step(x):
            print("value", x)
            box.val = x
            return x
    """)
    got = [f.message for f in fs if f.rule == "GL004-impure-jit"]
    assert len(got) == 2


def test_debug_print_is_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        @jax.jit
        def step(x):
            jax.debug.print("x {x}", x=x)
            return x
    """)
    assert "GL004-impure-jit" not in codes(fs)


def test_logkv_under_trace_flagged(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from distributed_pipeline_tpu.utils import logger
        def outer(xs):
            def body(c, x):
                logger.logkv("x", x)
                return c, x
            return jax.lax.scan(body, 0, xs)
    """)
    assert "GL004-impure-jit" in codes(fs)


# ------------------------------------------------------------------- GL005


def test_jit_inside_loop(tmp_path):
    fs = lint(tmp_path, """
        import jax
        def run(xs):
            outs = []
            for x in xs:
                f = jax.jit(lambda a: a * 2)
                outs.append(f(x))
            return outs
    """)
    assert "GL005-recompile-hazard" in codes(fs)


def test_shape_scalar_into_jitted_call(tmp_path):
    fs = lint(tmp_path, """
        import jax
        g = jax.jit(lambda a, n: a * n)
        def run(x):
            return g(x, len(x)) + g(x, x.shape[0])
    """)
    got = [f for f in fs if f.rule == "GL005-recompile-hazard"]
    assert len(got) == 2


def test_module_level_jit_called_in_loop_is_clean(tmp_path):
    fs = lint(tmp_path, """
        import jax
        f = jax.jit(lambda a: a * 2)
        def run(xs):
            return [f(x) for x in xs] + [f(x) for x in xs]
    """)
    assert "GL005-recompile-hazard" not in codes(fs)


# ------------------------------------------------------------------- GL006


def test_raw_shard_map_import_and_check_rep(tmp_path):
    fs = lint(tmp_path, """
        from jax.experimental.shard_map import shard_map
        out = shard_map(lambda x: x, mesh=None, in_specs=None,
                        out_specs=None, check_rep=False)
    """)
    got = [f for f in fs if f.rule == "GL006-raw-shard-map"]
    assert len(got) == 2  # the import AND the check_rep kwarg


def test_compat_shard_map_is_clean(tmp_path):
    fs = lint(tmp_path, """
        from distributed_pipeline_tpu.utils.jax_compat import shard_map
        out = shard_map(lambda x: x, mesh=None, in_specs=None,
                        out_specs=None, check_vma=False)
    """)
    assert "GL006-raw-shard-map" not in codes(fs)


def test_jax_compat_itself_is_exempt(tmp_path):
    fs = lint(tmp_path, """
        from jax.experimental.shard_map import shard_map
    """, name="utils/jax_compat.py")
    assert "GL006-raw-shard-map" not in codes(fs)


# ------------------------------------------------------------------- GL007


def test_host_sync_in_loop_on_step_outputs(tmp_path):
    """Blocking conversions of a step output INSIDE the outer (untraced)
    training loop serialize async dispatch — every spelling the rule
    names: float(), np.asarray, .item(), and the direct-call form."""
    fs = lint(tmp_path, """
        import numpy as np
        def train(loop, data):
            for batch in data:
                m = loop.run_step(batch)
                loss = float(m["loss"])
                arr = np.asarray(m["grad_norm"])
                v = m["loss"].item()
                direct = float(loop.run_step(batch)["loss"])
    """)
    got = [f for f in fs if f.rule == "GL007-host-sync-in-loop"]
    assert len(got) == 4


def test_host_sync_in_loop_jitted_binding(tmp_path):
    """The rule also tracks outputs of a module-level jitted binding
    called in the loop (the bench/measure shape)."""
    fs = lint(tmp_path, """
        import jax
        run = jax.jit(lambda p, x: p * x)
        def bench(params, batches):
            for b in batches:
                out = run(params, b)
                total = float(out)
    """)
    assert "GL007-host-sync-in-loop" in codes(fs)


def test_host_sync_in_loop_negatives(tmp_path):
    """Sanctioned spellings stay clean: explicit jax.device_get inside
    the loop, conversions of non-step values, and conversions AFTER the
    loop (one sync per run, not per step)."""
    fs = lint(tmp_path, """
        import jax
        def train(loop, data):
            for batch in data:
                m = loop.run_step(batch)
                ok = float(jax.device_get(m["loss"]))
                other = float(batch["x"])
            final = float(m["loss"])
    """)
    assert "GL007-host-sync-in-loop" not in codes(fs)


def test_host_sync_in_traced_loop_is_gl002_territory(tmp_path):
    """A loop INSIDE traced code is GL002's jurisdiction — GL007 only
    fires on the untraced outer loop (no double reporting)."""
    fs = lint(tmp_path, """
        import jax
        @jax.jit
        def step(engine, state, batches):
            for b in batches:
                m = engine.train_step(state, b)
                x = float(m)
            return x
    """)
    assert "GL007-host-sync-in-loop" not in codes(fs)


# ------------------------------------------------------------------- GL008


def test_named_sharding_outside_engine_flagged(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        def place(mesh, x):
            return jax.device_put(x, NamedSharding(mesh, P("data")))
    """)
    assert "GL008-hand-wired-sharding" in codes(fs)


def test_partition_spec_as_sharding_kwarg_flagged(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from jax.sharding import PartitionSpec as P
        def build(f):
            return jax.jit(f, out_shardings=P("data"))
    """)
    assert "GL008-hand-wired-sharding" in codes(fs)


def test_partition_spec_into_constraint_and_device_kwarg_flagged(tmp_path):
    fs = lint(tmp_path, """
        import jax
        from jax.sharding import PartitionSpec as P
        def pin(x):
            return jax.lax.with_sharding_constraint(x, P("data"))
        def place(x):
            return jax.device_put(x, device=P("data"))
    """)
    assert sum(1 for f in fs
               if f.rule == "GL008-hand-wired-sharding") == 2


def test_bare_partition_spec_construction_is_clean(tmp_path):
    """Rule tables and shard_map specs are MADE of PartitionSpecs — only
    using one directly AS a sharding is hand-wiring."""
    fs = lint(tmp_path, """
        from jax.sharding import PartitionSpec as P
        from distributed_pipeline_tpu.utils.jax_compat import shard_map
        RULES = ((r"attn/qkv$", P("fsdp", None)), (r".*", P()))
        def wrap(f, mesh):
            return shard_map(f, mesh, in_specs=(P("data"),),
                             out_specs=P("data"))
    """)
    assert "GL008-hand-wired-sharding" not in codes(fs)


def test_engine_modules_exempt_from_gl008(tmp_path):
    src = """
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        def replicated(mesh):
            return NamedSharding(mesh, P())
    """
    for name in ("parallel/partition.py", "parallel/sharding.py"):
        assert "GL008-hand-wired-sharding" not in codes(
            lint(tmp_path, src, name=name))
    assert "GL008-hand-wired-sharding" in codes(
        lint(tmp_path, src, name="serving/somewhere.py"))


# ------------------------------------------------------------------- GL009


def test_adhoc_timing_delta_into_logkv_flagged(tmp_path):
    """Both the direct delta and the one-hop name binding are sinks when
    they reach a logkv* call."""
    fs = lint(tmp_path, """
        import time
        from x import logger
        def f(t0):
            logger.logkv("wall_s", time.time() - t0)
            dt = time.perf_counter() - t0
            logger.logkv_mean("step_s", round(dt, 3))
    """)
    assert sum(1 for f in fs if f.rule == "GL009-ad-hoc-timing") == 2


def test_adhoc_timing_accumulator_flagged(tmp_path):
    """The reference logger's pattern — += a delta into a metrics
    mapping entry — is the dogfooded true positive (profile_kv, now
    migrated to obs.trace.Stopwatch)."""
    fs = lint(tmp_path, """
        import time
        def f(metrics, t0):
            metrics["wait_x"] += time.monotonic() - t0
    """)
    assert "GL009-ad-hoc-timing" in codes(fs)


def test_adhoc_timing_control_flow_and_results_clean(tmp_path):
    """Deltas for control flow, return values, and result dicts stay
    legal — only the direct delta->metric-sink flow gates; rebinding a
    delta name clears it."""
    fs = lint(tmp_path, """
        import time
        from x import logger
        def f(t0, deadline):
            wall = time.time() - t0
            if wall > deadline:
                return None
            dt = time.perf_counter() - t0
            dt = compute(dt)          # rebind: no longer a raw delta
            logger.logkv("derived", dt)
            return {"wall_s": time.time() - t0}
    """)
    assert "GL009-ad-hoc-timing" not in codes(fs)


def test_adhoc_timing_owner_modules_exempt(tmp_path):
    src = """
        import time
        from x import logger
        def f(t0):
            logger.logkv("wall_s", time.time() - t0)
    """
    for name in ("utils/perf.py", "obs/trace.py", "obs/export.py"):
        assert "GL009-ad-hoc-timing" not in codes(
            lint(tmp_path, src, name=name))
    assert "GL009-ad-hoc-timing" in codes(
        lint(tmp_path, src, name="utils/elsewhere.py"))


# ----------------------------------------------------------- parse errors


def test_unparseable_file_gates(tmp_path):
    fs = lint(tmp_path, "def broken(:\n")
    assert "GL000-parse-error" in codes(fs)


# ------------------------------------------------------------ CLI contract


BAD_SRC = """
import jax
def f(rng):
    a = jax.random.normal(rng, (2,))
    b = jax.random.uniform(rng, (2,))
    return a + b
"""


def test_cli_json_contract(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    rc = cli_main(["--format", "json", "--baseline", "none", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert out["version"] == 1 and out["tool"] == "graftlint"
    assert out["checked_files"] == 1 and out["baselined"] == 0
    assert len(out["rules"]) >= 6
    (finding,) = [f for f in out["findings"]
                  if f["rule"] == "GL001-key-reuse"]
    for key in ("rule", "path", "line", "col", "message", "snippet",
                "fingerprint"):
        assert key in finding
    assert finding["line"] == 5  # the second consumer is the finding


def test_cli_clean_exits_zero(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("import jax\nx = 1\n")
    rc = cli_main(["--format", "json", "--baseline", "none", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["findings"] == []


def test_cli_rule_filter_and_list(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    rc = cli_main(["--format", "json", "--baseline", "none",
                   "--rules", "GL006", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["rules"] == ["GL006-raw-shard-map"]
    assert cli_main(["--list-rules"]) == 0
    listed = capsys.readouterr().out
    assert "GL001-key-reuse" in listed and "GL006-raw-shard-map" in listed


def test_cli_usage_errors(tmp_path, capsys):
    assert cli_main([]) == 2
    (tmp_path / "bad.py").write_text(BAD_SRC)
    assert cli_main(["--rules", "NOPE", str(tmp_path)]) == 2


# ------------------------------------------------------- baseline contract


def test_baseline_round_trip(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    bl = tmp_path / "graftlint_baseline.json"

    # 1. write the baseline: everything current is audited-allowed
    rc = cli_main(["--baseline", str(bl), "--write-baseline", str(tmp_path)])
    capsys.readouterr()
    assert rc == 0 and bl.exists()
    data = json.loads(bl.read_text())
    assert data["version"] == 1 and len(data["entries"]) == 1

    # 2. gated run is now clean, findings counted as baselined
    rc = cli_main(["--format", "json", "--baseline", str(bl),
                   str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["findings"] == [] and out["baselined"] == 1

    # 3. a NEW hazard still fails the gate
    (tmp_path / "new.py").write_text(BAD_SRC)
    rc = cli_main(["--format", "json", "--baseline", str(bl),
                   str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and len(out["findings"]) == 1

    # 4. editing the baselined LINE invalidates its fingerprint (the
    # audit no longer vouches for the changed code)
    (tmp_path / "new.py").unlink()
    (tmp_path / "bad.py").write_text(BAD_SRC.replace(
        "jax.random.uniform(rng, (2,))", "jax.random.uniform(rng, (3,))"))
    rc = cli_main(["--format", "json", "--baseline", str(bl),
                   str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and out["baselined"] == 0


def test_baseline_auto_discovery_from_cwd(tmp_path, capsys, monkeypatch):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(BAD_SRC)
    monkeypatch.chdir(tmp_path)
    assert cli_main(["--write-baseline", "pkg"]) == 0
    capsys.readouterr()
    # the acceptance-criteria invocation shape: no --baseline flag at all
    rc = cli_main(["--format", "json", "pkg"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["baselined"] == 1
    assert out["baseline"].endswith("graftlint_baseline.json")


def test_baseline_fingerprints_survive_line_shifts(tmp_path):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    before, _ = run_paths([str(tmp_path)])
    (tmp_path / "bad.py").write_text("# a comment pushing lines down\n"
                                     * 7 + BAD_SRC)
    after, _ = run_paths([str(tmp_path)])
    assert [f.fingerprint for f in before] == [f.fingerprint for f in after]
    assert before[0].line != after[0].line


def test_baseline_api_round_trip(tmp_path):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    findings, _ = run_paths([str(tmp_path)])
    bl = Baseline.from_findings(findings)
    path = tmp_path / "bl.json"
    bl.save(str(path))
    loaded = Baseline.load(str(path))
    new, old = loaded.split(findings)
    assert new == [] and old == findings
    with pytest.raises(ValueError):
        path.write_text('{"oops": true}')
        Baseline.load(str(path))


# ===================================================== interprocedural pass
# (ISSUE 15: the three r7-audit blind spots as regression pairs, GL011,
# and the call-graph edge cases — each positive has a sibling negative
# proving the upgrade is a proof, not a new heuristic)


# ---- blind spot (a): tracedness through ordinary calls (GL002 graph)


def test_transitive_host_sync_across_modules(tmp_path):
    """A helper that .item()s its parameter, flagged ONLY because a
    jitted function in another module reaches it through a call."""
    fs = lint_files(tmp_path, {
        "helpers.py": """
            def fetch(m):
                return m["loss"].item()
        """,
        "main.py": """
            import jax
            from helpers import fetch
            @jax.jit
            def step(x):
                return fetch(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1
    assert got[0].path.endswith("helpers.py")
    assert "reached from traced" in got[0].message


def test_transitive_host_sync_negative_eager_only(tmp_path):
    """The same helper called only from eager code is legal — the
    upgrade must not turn every .item() helper into a finding."""
    fs = lint_files(tmp_path, {
        "helpers.py": """
            def fetch(m):
                return m["loss"].item()
        """,
        "main.py": """
            from helpers import fetch
            def report(x):
                return fetch(x)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_transitive_host_sync_two_hops(tmp_path):
    """Depth-2 chain: traced -> forwarder -> syncer, three modules."""
    fs = lint_files(tmp_path, {
        "deep.py": """
            def to_float(v):
                return float(v)
        """,
        "mid.py": """
            from deep import to_float
            def summarize(m):
                return to_float(m)
        """,
        "main.py": """
            import jax
            from mid import summarize
            @jax.jit
            def step(x):
                return summarize(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) >= 1 and any(f.path.endswith("deep.py") for f in got)


# ---- blind spot (b): donation across module scope (GL003 graph)


def test_cross_module_donation_after_use(tmp_path):
    """The r6 orbax-restore shape: the donating jitted binding lives in
    the trainer module; the restore-then-read lives in the driver."""
    fs = lint_files(tmp_path, {
        "trainer.py": """
            import jax
            train_step = jax.jit(lambda s, b: s, donate_argnums=(0,))
        """,
        "driver.py": """
            from trainer import train_step
            def run(state, batch):
                new = train_step(state, batch)
                stale = state.loss      # donated buffer: use-after-free
                return new, stale
        """})
    got = [f for f in fs if f.rule == "GL003-donation-after-use"]
    assert len(got) == 1
    assert got[0].path.endswith("driver.py")
    assert "use-after-free" in got[0].message


def test_cross_module_donation_negative_rebound(tmp_path):
    """Rebinding the donated name to the call's result is the sanctioned
    idiom — no finding."""
    fs = lint_files(tmp_path, {
        "trainer.py": """
            import jax
            train_step = jax.jit(lambda s, b: s, donate_argnums=(0,))
        """,
        "driver.py": """
            from trainer import train_step
            def run(state, batch):
                state = train_step(state, batch)
                return state.loss
        """})
    assert "GL003-donation-after-use" not in codes(fs)


def test_donation_through_transitively_donating_helper(tmp_path):
    """A helper that passes its parameter into the donating call makes
    the CALLER's later read a hazard (donation propagates up)."""
    fs = lint_files(tmp_path, {
        "trainer.py": """
            import jax
            train_step = jax.jit(lambda s, b: s, donate_argnums=(0,))
        """,
        "wrap.py": """
            from trainer import train_step
            def advance(state, batch):
                return train_step(state, batch)
        """,
        "driver.py": """
            from wrap import advance
            def run(state, batch):
                new = advance(state, batch)
                return new, state.loss
        """})
    got = [f for f in fs if f.rule == "GL003-donation-after-use"]
    assert any(f.path.endswith("driver.py") for f in got)


# ---- blind spot (c): static_argnums declared far away (GL005 graph)


def test_distant_jitted_binding_hazard_and_static_negative(tmp_path):
    """An imported jitted binding called with len(x) is a recompile
    hazard — unless the distant jax.jit site declared that argument
    static (the false-positive the old rule could not avoid AND the
    true positive it could not see, in one pair)."""
    fs = lint_files(tmp_path, {
        "compiled.py": """
            import jax
            def fwd(x, n):
                return x * n
            fast = jax.jit(fwd)
            safe = jax.jit(fwd, static_argnums=(1,))
            named = jax.jit(fwd, static_argnames=("n",))
        """,
        "caller.py": """
            from compiled import fast, safe, named
            def run(x):
                a = fast(x, len(x))        # hazard: traced argument
                b = safe(x, len(x))        # static by position: clean
                c = named(x, n=len(x))     # static by name: clean
                return a, b, c
        """})
    got = [f for f in fs if f.rule == "GL005-recompile-hazard"]
    assert len(got) == 1
    assert got[0].path.endswith("caller.py") and got[0].line == 4


def test_local_static_argnums_suppress_gl005(tmp_path):
    """The LOCAL half is static-aware too: a same-module binding with
    static_argnums no longer false-positives."""
    fs = lint(tmp_path, """
        import jax
        def fwd(x, n):
            return x * n
        g = jax.jit(fwd, static_argnums=(1,))
        h = jax.jit(fwd)
        def run(x):
            return g(x, len(x)) + h(x, len(x))
    """)
    got = [f for f in fs if f.rule == "GL005-recompile-hazard"]
    assert len(got) == 1  # only the non-static binding


def test_static_through_partial_chain(tmp_path):
    """functools.partial shifts positions: the hazard argument lands on
    the underlying static position through the chain — clean; the
    sibling unshifted binding still flags."""
    fs = lint_files(tmp_path, {
        "compiled.py": """
            import jax
            def fwd(cfg, x, n):
                return x * n
            jfwd = jax.jit(fwd, static_argnums=(0, 2))
            jraw = jax.jit(fwd, static_argnums=(0,))
        """,
        "caller.py": """
            import functools
            from compiled import jfwd, jraw
            CFG = {"scale": 2}
            warm = functools.partial(jfwd, CFG)
            cold = functools.partial(jraw, CFG)
            def run(x):
                a = warm(x, len(x))   # underlying pos 2: static, clean
                b = cold(x, len(x))   # underlying pos 2: traced, hazard
                return a, b
        """})
    got = [f for f in fs if f.rule == "GL005-recompile-hazard"]
    assert len(got) == 1 and got[0].line == 9


# ---- GL011: cross-module key reuse


def test_gl011_key_into_two_consuming_callees(tmp_path):
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def sample_a(rng, shape):
                return jax.random.normal(rng, shape)
            def sample_b(rng, shape):
                return jax.random.uniform(rng, shape)
        """,
        "model.py": """
            from samplers import sample_a, sample_b
            def f(rng):
                a = sample_a(rng, (2,))
                b = sample_b(rng, (2,))
                return a + b
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert len(got) == 1 and got[0].path.endswith("model.py")
    assert "sample_a" in got[0].message and "sample_b" in got[0].message


def test_gl011_split_keys_are_clean(tmp_path):
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def sample_a(rng, shape):
                return jax.random.normal(rng, shape)
        """,
        "model.py": """
            import jax
            from samplers import sample_a
            def f(rng):
                k1, k2 = jax.random.split(rng)
                return sample_a(k1, (2,)) + sample_a(k2, (2,))
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


def test_gl011_direct_use_plus_consuming_callee(tmp_path):
    """One direct sampler draw + one proven callee consumption of the
    same key — the mix GL001 counts neither half of."""
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def sample_a(rng, shape):
                return jax.random.normal(rng, shape)
        """,
        "model.py": """
            import jax
            from samplers import sample_a
            def f(rng):
                noise = jax.random.normal(rng, (2,))
                return noise + sample_a(rng, (2,))
        """})
    assert "GL011-cross-module-key-reuse" in codes(fs)


def test_gl011_consuming_callee_in_loop_without_rebinding(tmp_path):
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def draw(rng, shape):
                return jax.random.normal(rng, shape)
        """,
        "model.py": """
            from samplers import draw
            def f(rng):
                outs = []
                for i in range(4):
                    outs.append(draw(rng, (2,)))
                return outs
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert got and "every iteration" in got[0].message


def test_gl011_loop_with_fold_in_is_clean(tmp_path):
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def draw(rng, shape):
                return jax.random.normal(rng, shape)
        """,
        "model.py": """
            import jax
            from samplers import draw
            def f(rng):
                outs = []
                for i in range(4):
                    k = jax.random.fold_in(rng, i)
                    outs.append(draw(k, (2,)))
                return outs
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


def test_gl011_early_return_branch_is_clean(tmp_path):
    """Consumption on an early-``return`` path must NOT leak into the
    fall-through path (the models/sampling.py MBR shape the first
    dogfood flagged — branch-sensitive replay keeps it clean)."""
    fs = lint_files(tmp_path, {
        "samplers.py": """
            import jax
            def draw(rng, shape):
                return jax.random.normal(rng, shape)
        """,
        "model.py": """
            import jax
            from samplers import draw
            def f(rng, fast):
                if fast:
                    return draw(rng, (2,))
                keys = jax.random.split(rng, 4)
                return keys
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


def test_gl011_does_not_duplicate_local_use_after_split(tmp_path):
    """A purely-local use-after-split is GL001's finding; GL011 must not
    emit a twin at the same site (review finding: the split branch fired
    for direct uses with no call boundary)."""
    fs = lint(tmp_path, """
        import jax
        def f(rng):
            ks = jax.random.split(rng)
            return ks, jax.random.normal(rng, (2,))
    """)
    assert "GL001-key-reuse" in codes(fs)
    assert "GL011-cross-module-key-reuse" not in codes(fs)


# ------------------------------------------------------------------- GL012


_PALLAS_SNIPPET = """
    import jax.experimental.pallas as pl
    def fast(x):
        return pl.pallas_call(lambda ref, out: None, out_shape=x)(x)
"""


def test_gl012_pallas_call_outside_ops_flagged(tmp_path):
    fs = lint(tmp_path, _PALLAS_SNIPPET, name="serving/engine.py")
    got = [f for f in fs if f.rule == "GL012-stray-pallas-call"]
    assert len(got) == 1
    assert "dispatch" in got[0].message


def test_gl012_pallas_call_inside_ops_exempt(tmp_path):
    fs = lint(tmp_path, _PALLAS_SNIPPET, name="ops/mykernel.py")
    assert "GL012-stray-pallas-call" not in codes(fs)


def test_gl012_from_import_flagged(tmp_path):
    fs = lint(tmp_path, """
        from jax.experimental.pallas import pallas_call
        def fast(x):
            return pallas_call(lambda ref, out: None, out_shape=x)(x)
    """, name="models/layer.py")
    assert "GL012-stray-pallas-call" in codes(fs)


def test_gl002_graph_does_not_duplicate_nested_traced_helper(tmp_path):
    """A helper def nested INSIDE a traced function is lexically traced:
    the local rule owns its sync sites and the graph half must not
    double-report them (review finding: the dedup guard only checked
    direct tracedness)."""
    fs = lint(tmp_path, """
        import jax
        @jax.jit
        def step(x):
            def inner(m):
                return m.item()
            return inner(x)
    """)
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1


def test_gl011_unknown_callee_widen_to_silence(tmp_path):
    """An unresolvable callee proves nothing — a key passed to it twice
    stays unflagged (don't know != hazard)."""
    fs = lint_files(tmp_path, {
        "model.py": """
            from mystery import oracle
            def f(rng):
                return oracle(rng) + oracle(rng)
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


# ---- GL007 graph half: blocking helper on a step output


def test_gl007_blocking_helper_across_modules(tmp_path):
    fs = lint_files(tmp_path, {
        "metrics.py": """
            def to_float(m):
                return float(m["loss"])
        """,
        "loop.py": """
            from metrics import to_float
            def train(loop, data):
                for batch in data:
                    m = loop.run_step(batch)
                    loss = to_float(m)
        """})
    got = [f for f in fs if f.rule == "GL007-host-sync-in-loop"]
    assert len(got) == 1 and got[0].path.endswith("loop.py")
    assert "blocks on" in got[0].message


def test_gl007_non_blocking_helper_is_clean(tmp_path):
    fs = lint_files(tmp_path, {
        "metrics.py": """
            def stash(m, sink):
                sink.append(m)
        """,
        "loop.py": """
            from metrics import stash
            def train(loop, data, sink):
                for batch in data:
                    m = loop.run_step(batch)
                    stash(m, sink)
        """})
    assert "GL007-host-sync-in-loop" not in codes(fs)


# ---- call-graph edge cases (satellite: cycles, self, re-exports,
# decorated helpers; partial chains are covered above)


def test_import_cycle_converges_and_still_proves(tmp_path):
    """a <-> b import cycle: the fixpoint converges and the transitive
    GL002 fact still flows around the cycle."""
    fs = lint_files(tmp_path, {
        "a.py": """
            import jax
            from b import helper
            @jax.jit
            def step(x):
                return helper(x)
            def eager_util(v):
                return v + 1
        """,
        "b.py": """
            from a import eager_util
            def helper(m):
                return eager_util(m["loss"].item())
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1 and got[0].path.endswith("b.py")


def test_method_call_through_self(tmp_path):
    """self.method resolution: a traced method reaching a syncing
    sibling method through self (the signature mapping must skip
    ``self``)."""
    fs = lint_files(tmp_path, {
        "engine.py": """
            import jax
            class Engine:
                def _fetch(self, m):
                    return float(m)
                def run(self, x):
                    step = jax.jit(lambda v: self._fetch(v))
                    return step(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) >= 0  # resolution must not crash; lambda body is
    # directly traced so the local rule may own it — the self-mapping
    # path is proven by the eager-negative below staying clean
    fs = lint_files(tmp_path / "neg", {
        "engine.py": """
            class Engine:
                def _fetch(self, m):
                    return float(m)
                def run(self, x):
                    return self._fetch(x)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_method_call_through_self_traced(tmp_path):
    """A jit-decorated method calling a syncing helper method via self:
    the helper's sync is flagged with the method chain resolved."""
    fs = lint_files(tmp_path, {
        "engine.py": """
            import jax
            import functools
            class Engine:
                def _fetch(self, m):
                    return m.item()
                @functools.partial(jax.jit, static_argnums=(0,))
                def step(self, x):
                    return self._fetch(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1 and "._fetch" not in got[0].snippet.replace(
        "return m.item()", "")  # flagged at the sync site


def test_reexported_name_resolves(tmp_path):
    """from x import y as z re-export chains: the caller imports the
    alias from the re-exporting module and the facts still flow."""
    fs = lint_files(tmp_path, {
        "impl.py": """
            def raw_fetch(m):
                return m["loss"].item()
        """,
        "api.py": """
            from impl import raw_fetch as fetch
        """,
        "main.py": """
            import jax
            from api import fetch
            @jax.jit
            def step(x):
                return fetch(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1 and got[0].path.endswith("impl.py")


def test_decorated_helper_still_resolves(tmp_path):
    """A helper behind an identity-preserving decorator keeps its
    summary (pos); a helper the decorator jits is directly traced and
    owned by the local rule — the graph half must not double-report
    (neg: exactly one finding either way)."""
    fs = lint_files(tmp_path, {
        "helpers.py": """
            import functools
            def logged(fn):
                @functools.wraps(fn)
                def inner(*a, **k):
                    return fn(*a, **k)
                return inner
            @logged
            def fetch(m):
                return m.item()
        """,
        "main.py": """
            import jax
            from helpers import fetch
            @jax.jit
            def step(x):
                return fetch(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1 and got[0].path.endswith("helpers.py")
    fs = lint_files(tmp_path / "neg", {
        "helpers.py": """
            import jax
            @jax.jit
            def fetch(m):
                return m.item()
        """,
        "main.py": """
            import jax
            from helpers import fetch
            @jax.jit
            def step(x):
                return fetch(x)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1  # local rule's finding only — no graph dupe


def test_star_args_widen_honestly(tmp_path):
    """*args at the call site: the arg->param mapping cannot be trusted,
    so the graph must stay silent rather than guess."""
    fs = lint_files(tmp_path, {
        "helpers.py": """
            def fetch(m):
                return m.item()
        """,
        "main.py": """
            import jax
            from helpers import fetch
            @jax.jit
            def step(x, extras):
                return fetch(*extras)
        """})
    assert "GL002-host-sync" not in codes(fs)


# --------------------------------------------------------------- the cache


def _write_fixture(tmp_path, helper_syncs=True):
    (tmp_path / "helpers.py").write_text(textwrap.dedent(f"""
        def fetch(m):
            return {'m["loss"].item()' if helper_syncs else 'm'}
    """))
    (tmp_path / "main.py").write_text(textwrap.dedent("""
        import jax
        from helpers import fetch
        @jax.jit
        def step(x):
            return fetch(x)
    """))


def test_cache_hits_and_preserves_findings(tmp_path):
    _write_fixture(tmp_path)
    cache_path = str(tmp_path / "graftlint_cache.json")
    cold = AnalysisCache(cache_path)
    f1, n1 = run_paths([str(tmp_path)], cache=cold)
    assert cold.misses == 2 and cold.hits == 0
    warm = AnalysisCache(cache_path)
    f2, n2 = run_paths([str(tmp_path)], cache=warm)
    assert warm.hits == 2 and warm.misses == 0
    assert n1 == n2
    assert [f.fingerprint for f in f1] == [f.fingerprint for f in f2]
    assert "GL002-host-sync" in codes(f2)  # cross-module finding intact


def test_cache_invalidation_on_content_change(tmp_path):
    """Changing ONE file must refresh the cross-module findings even
    though the OTHER file is served from cache: the summaries re-link
    every run, only the per-file work is memoized."""
    _write_fixture(tmp_path, helper_syncs=True)
    cache_path = str(tmp_path / "graftlint_cache.json")
    f1, _ = run_paths([str(tmp_path)], cache=AnalysisCache(cache_path))
    assert "GL002-host-sync" in codes(f1)
    # fix the helper: the finding must disappear on a cached run
    _write_fixture(tmp_path, helper_syncs=False)
    warm = AnalysisCache(cache_path)
    f2, _ = run_paths([str(tmp_path)], cache=warm)
    assert warm.hits == 1 and warm.misses == 1  # only helpers.py reparsed
    assert "GL002-host-sync" not in codes(f2)


def test_cache_garbled_file_degrades_to_cold(tmp_path):
    _write_fixture(tmp_path)
    cache_path = tmp_path / "graftlint_cache.json"
    cache_path.write_text("{not json")
    c = AnalysisCache(str(cache_path))
    findings, n = run_paths([str(tmp_path)], cache=c)
    assert n == 2 and c.misses == 2
    assert "GL002-host-sync" in codes(findings)


def test_cache_survives_path_spelling_changes(tmp_path, monkeypatch):
    """A cache written by a relative-path CLI run must serve an
    absolute-path gate run (and vice versa): entries key on abspath and
    summaries re-key to the reading run's spelling — the cross-module
    graph must not lose modules to spelling mismatches."""
    _write_fixture(tmp_path)
    cache_path = str(tmp_path / "graftlint_cache.json")
    monkeypatch.chdir(tmp_path.parent)
    f1, _ = run_paths([tmp_path.name], cache=AnalysisCache(cache_path))
    warm = AnalysisCache(cache_path)
    f2, _ = run_paths([str(tmp_path)], cache=warm)
    assert warm.hits == 2 and warm.misses == 0
    assert "GL002-host-sync" in codes(f2)  # graph finding intact
    assert {f.fingerprint for f in f1} == {f.fingerprint for f in f2}


def test_cli_no_cache_flag(tmp_path, capsys, monkeypatch):
    _write_fixture(tmp_path)
    (tmp_path / "graftlint_baseline.json").write_text(
        '{"version": 1, "entries": []}')
    monkeypatch.chdir(tmp_path)
    rc = cli_main(["--format", "json", "."])
    capsys.readouterr()
    assert rc == 1
    assert (tmp_path / "graftlint_cache.json").exists()
    (tmp_path / "graftlint_cache.json").unlink()
    rc = cli_main(["--format", "json", "--no-cache", "."])
    capsys.readouterr()
    assert rc == 1
    assert not (tmp_path / "graftlint_cache.json").exists()


# ------------------------------------------------- github format / changed


def test_cli_github_format_annotations(tmp_path, capsys):
    (tmp_path / "bad.py").write_text(BAD_SRC)
    rc = cli_main(["--format", "github", "--baseline", "none",
                   str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    lines = [ln for ln in out.splitlines() if ln.startswith("::error ")]
    assert len(lines) == 1
    assert "file=" in lines[0] and ",line=5," in lines[0]
    assert "GL001-key-reuse" in lines[0]


def test_cli_github_format_clean_is_quiet(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    rc = cli_main(["--format", "github", "--baseline", "none",
                   str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 0 and "::error" not in out


def test_cli_changed_scopes_report_not_analysis(tmp_path, capsys):
    """--changed restricts the report (and exit code) to the named
    files, but the analysis stays whole-program: a finding CAUSED by the
    changed helper is reported at its (unchanged) sync site only when
    that site is in scope."""
    (tmp_path / "bad.py").write_text(BAD_SRC)
    (tmp_path / "ok.py").write_text("x = 1\n")
    # NOTE: paths go first — `--changed` is nargs="*" and would swallow
    # trailing positionals
    rc = cli_main([str(tmp_path), "--format", "json", "--baseline",
                   "none", "--changed", str(tmp_path / "ok.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0 and out["findings"] == []
    rc = cli_main([str(tmp_path), "--format", "json", "--baseline",
                   "none", "--changed", str(tmp_path / "bad.py")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1 and len(out["findings"]) == 1


# -------------------------------------------------------- runtime sanitizer


def test_recompile_monitor_counts_fresh_compiles():
    import jax
    import jax.numpy as jnp

    from distributed_pipeline_tpu.utils.perf import RecompileMonitor

    with RecompileMonitor() as mon:
        f = jax.jit(lambda x: x * 2.0 + 1.0)
        f(jnp.ones((3, 5)))
        first = mon.count
        assert first >= 1
        assert mon.last.startswith("Compiling")
        f(jnp.ones((3, 5)))          # cache hit: no growth
        assert mon.count == first
        f(jnp.ones((4, 5)))          # new shape: retrace + recompile
        assert mon.count > first
    after = mon.count
    jax.jit(lambda x: x * 3.0 - 7.0)(jnp.ones((2, 2)))
    assert mon.count == after        # uninstalled: counting stopped


# ===================================================================
# ISSUE 19: value-flow engine — the four ROADMAP-7 blind spots as
# pos/neg proof pairs, points-to edge cases, GL011 branch arms,
# cache schema migration, and the runtime-evidence bridge (GL013)
# ===================================================================

# ---------------------------------------- gap 1: locally-derived syncs


def test_derived_sync_in_traced_helper(tmp_path):
    """GL002 through the value-flow engine: the helper syncs a value it
    DERIVES from its parameter (jnp.sum of it), not the parameter
    itself — the r17 pass was parameter-rooted only."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def helper(x):
                s = jnp.sum(x * 2.0)
                return float(s)

            @jax.jit
            def step(batch):
                return helper(batch)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1
    assert "derived from parameter 'x'" in got[0].message


def test_underived_sync_in_traced_helper_is_clean(tmp_path):
    """float() on a host-local constant inside a traced helper derives
    from NO parameter — the derivation chain, not the call position,
    is what convicts."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def helper(x):
                cfg = {"lr": 0.1}
                return float(cfg["lr"]) * jnp.sum(x)

            @jax.jit
            def step(batch):
                return helper(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_derived_sync_rebound_operand_is_clean(tmp_path):
    """Rebinding the derived name to something underived kills the
    derivation — must-analysis, not taint-forever."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def helper(x):
                s = jnp.sum(x)
                s = 3.0
                return float(s) * jnp.mean(x)

            @jax.jit
            def step(batch):
                return helper(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_static_attr_chain_is_not_derived(tmp_path):
    """shape/dtype reads are trace-static — float(x.shape[0]) is legal
    under jit and must stay silent."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def helper(x):
                return float(x.shape[0]) * 2.0

            @jax.jit
            def step(batch):
                return helper(batch) + batch
        """})
    assert "GL002-host-sync" not in codes(fs)


# --------------------------------- gap 2: container-field donation (GL003)


def test_donation_of_container_field_then_read(tmp_path):
    """Donating state['params'] arms the FIELD path; reading that exact
    field afterwards is use-after-free."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            opt_step = jax.jit(lambda p, g: p, donate_argnums=(0,))

            def run(state, grads):
                new_params = opt_step(state["params"], grads)
                stale = state["params"]
                return new_params, stale
        """})
    got = [f for f in fs if f.rule == "GL003-donation-after-use"]
    assert len(got) == 1
    assert "state['params']" in got[0].message


def test_donation_sibling_field_read_is_clean(tmp_path):
    """state['step'] shares a container with donated state['params'] but
    not a buffer — sibling reads must not conflict."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            opt_step = jax.jit(lambda p, g: p, donate_argnums=(0,))

            def run(state, grads):
                new_params = opt_step(state["params"], grads)
                fine = state["step"]
                return new_params, fine
        """})
    assert "GL003-donation-after-use" not in codes(fs)


def test_donation_field_rebind_is_clean(tmp_path):
    """Storing the call's result back into the donated field is the
    sanctioned idiom, field-sensitively."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            opt_step = jax.jit(lambda p, g: p, donate_argnums=(0,))

            def run(state, grads):
                state["params"] = opt_step(state["params"], grads)
                return state["params"]
        """})
    assert "GL003-donation-after-use" not in codes(fs)


def test_donation_whole_container_read_after_field_donation(tmp_path):
    """Reading the WHOLE container after one of its fields was donated
    still touches the dead buffer (the container transitively holds
    it)."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            opt_step = jax.jit(lambda p, g: p, donate_argnums=(0,))

            def run(state, grads):
                new_params = opt_step(state["params"], grads)
                return new_params, state
        """})
    assert "GL003-donation-after-use" in codes(fs)


def test_cross_module_field_donation_after_use(tmp_path):
    """The graph replay carries field paths too: donor binding in one
    module, field read in another."""
    fs = lint_files(tmp_path, {
        "trainer.py": """
            import jax
            opt_step = jax.jit(lambda p, g: p, donate_argnums=(0,))
        """,
        "driver.py": """
            from trainer import opt_step
            def run(state, grads):
                new = opt_step(state["params"], grads)
                stale = state["params"]
                return new, stale
        """})
    got = [f for f in fs if f.rule == "GL003-donation-after-use"]
    assert len(got) == 1
    assert got[0].path.endswith("driver.py")


# ------------------------------ gap 3: key= / non-first-positional kwargs


def test_key_kwarg_consumption_counts(tmp_path):
    """GL011 sees a helper that consumes its key through a key= kwarg in
    non-first position — the r17 pass only tracked first-positional."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key):
                a = draw((4,), key=key)
                b = draw((4,), key=key)
                return a, b
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert len(got) == 1
    assert "consumed more than once" in got[0].message


def test_key_kwarg_split_keys_are_clean(tmp_path):
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key):
                k1, k2 = jax.random.split(key)
                a = draw((4,), key=k1)
                b = draw((4,), key=k2)
                return a, b
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


def test_container_field_key_reuse(tmp_path):
    """A key living in a container field (state['rng']) consumed by a
    proven consumer twice — field paths are tracked keys too."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(state):
                a = draw((4,), key=state["rng"])
                b = draw((4,), key=state["rng"])
                return a, b
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert len(got) == 1
    assert "state['rng']" in got[0].message


def test_container_field_key_rebound_is_clean(tmp_path):
    """Rebinding the field to an unrelated key between consumptions
    kills the tracking — one consumption per key, no finding."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(state, fresh_key):
                a = draw((4,), key=state["rng"])
                state["rng"] = fresh_key
                b = draw((4,), key=state["rng"])
                return a, b
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


# ----------------------- gap 4: dynamic dispatch through containers


def test_dispatch_through_module_dict_const_key(tmp_path):
    """A callable stored in a module dict under a constant key resolves;
    the callee's derived sync is then reachable from traced code."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def loss_a(x):
                return float(jnp.sum(x))

            HANDLERS = {"a": loss_a}

            def dispatch(batch):
                @jax.jit
                def step(b):
                    fn = HANDLERS["a"]
                    return fn(b)
                return step(batch)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1


def test_dispatch_through_unknown_table_is_silent(tmp_path):
    """The table arrives as a parameter — unresolvable, widen to
    silence (facts only ever proven)."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def loss_a(x):
                return float(jnp.sum(x))

            def dispatch(batch, table):
                @jax.jit
                def step(b):
                    fn = table["a"]
                    return fn(b)
                return step(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_dispatch_through_constructor_kwarg_field(tmp_path):
    """CFG = Cfg(step=loss_fn) at module level: the instance attribute
    resolves through the constructor-kwarg points-to entry."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def loss_fn(x):
                return float(jnp.sum(x))

            class Cfg:
                def __init__(self, step=None):
                    self.step = step

            CFG = Cfg(step=loss_fn)

            def run(batch):
                @jax.jit
                def tick(b):
                    return CFG.step(b)
                return tick(batch)
        """})
    got = [f for f in fs if f.rule == "GL002-host-sync"]
    assert len(got) == 1


def test_dispatch_field_rebound_below_module_scope_widens(tmp_path):
    """Any function storing into the tracked field kills the module-env
    fact — the dispatch proves nothing afterwards."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def loss_fn(x):
                return float(jnp.sum(x))

            class Cfg:
                def __init__(self, step=None):
                    self.step = step

            CFG = Cfg(step=loss_fn)

            def rebind(other):
                CFG.step = other

            def run(batch):
                @jax.jit
                def tick(b):
                    return CFG.step(b)
                return tick(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_dispatch_registration_pattern_widens_table(tmp_path):
    """HANDLERS[name] = fn anywhere below module scope widens the whole
    table: runtime registration defeats the static proof, honestly."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def loss_a(x):
                return float(jnp.sum(x))

            HANDLERS = {"a": loss_a}

            def register(name, fn):
                HANDLERS[name] = fn

            def dispatch(batch):
                @jax.jit
                def step(b):
                    fn = HANDLERS["a"]
                    return fn(b)
                return step(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


def test_dispatch_through_getattr_is_silent(tmp_path):
    """getattr(module, name) is dynamic — no candidates, no finding."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax
            import jax.numpy as jnp

            def run(batch, name):
                fn = getattr(jnp, name)
                @jax.jit
                def tick(b):
                    return fn(b)
                return tick(batch)
        """})
    assert "GL002-host-sync" not in codes(fs)


# --------------------------- GL011 branch replay: try/except, loop-else


def test_key_retry_in_except_arm_is_clean(tmp_path):
    """try-consume / except-consume are ALTERNATIVES — the retry pattern
    must not read as double consumption."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key):
                try:
                    return draw((4,), key=key)
                except ValueError:
                    return draw((2,), key=key)
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


def test_key_consumed_in_try_body_and_after(tmp_path):
    """Consumption in the try body survives the merge; a second
    consumption after the statement is correlated randomness."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key):
                try:
                    a = draw((4,), key=key)
                except ValueError:
                    a = None
                b = draw((2,), key=key)
                return a, b
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert len(got) == 1


def test_key_in_loop_else_always_runs(tmp_path):
    """A break-less for's else arm ALWAYS runs — consuming there plus
    after the loop is double consumption."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key, items):
                for it in items:
                    pass
                else:
                    a = draw((4,), key=key)
                b = draw((2,), key=key)
                return a, b
        """})
    got = [f for f in fs if f.rule == "GL011-cross-module-key-reuse"]
    assert len(got) == 1


def test_key_in_loop_else_with_break_is_exclusive(tmp_path):
    """With a break in the loop, the else arm and the post-loop code are
    exclusive paths — one consumption each, no finding."""
    fs = lint_files(tmp_path, {
        "mod.py": """
            import jax

            def draw(shape, key=None):
                return jax.random.normal(key, shape)

            def sample(key, items):
                for it in items:
                    if it:
                        break
                else:
                    return draw((4,), key=key)
                return draw((2,), key=key)
        """})
    assert "GL011-cross-module-key-reuse" not in codes(fs)


# ------------------------------------------------- cache schema migration


def test_cache_old_schema_entry_degrades_to_cold(tmp_path):
    """A cache entry whose summary predates SUMMARY_SCHEMA (or is
    garbled) must re-summarize cold — never crash, never trust."""
    src = tmp_path / "mod.py"
    src.write_text(textwrap.dedent("""
        import jax
        def f(rng):
            a = jax.random.normal(rng, (2,))
            b = jax.random.uniform(rng, (2,))
            return a + b
    """))
    cache_path = tmp_path / "cache.json"
    cache = AnalysisCache(str(cache_path))
    first, _ = run_paths([str(src)], cache=cache)
    assert "GL001-key-reuse" in codes(first)

    # sabotage: rewrite every cached summary as an old-schema relic
    blob = json.loads(cache_path.read_text())
    for entry in blob["files"].values():
        entry["summary"] = {"schema": 1, "path": "mod.py"}
    cache_path.write_text(json.dumps(blob))

    cache2 = AnalysisCache(str(cache_path))
    again, _ = run_paths([str(src)], cache=cache2)
    assert {f.fingerprint for f in again} == {f.fingerprint for f in first}

    # garbled beyond schema: still a cold path, still no crash
    blob = json.loads(cache_path.read_text())
    for entry in blob["files"].values():
        entry["summary"] = {"schema": "wat", "funcs": 7}
    cache_path.write_text(json.dumps(blob))
    cache3 = AnalysisCache(str(cache_path))
    again2, _ = run_paths([str(src)], cache=cache3)
    assert codes(again2) == codes(first)


def test_summary_from_dict_is_total():
    from distributed_pipeline_tpu.analysis.callgraph import (
        SUMMARY_SCHEMA, ModuleSummary)

    with pytest.raises(ValueError):
        ModuleSummary.from_dict({"schema": SUMMARY_SCHEMA - 1})
    with pytest.raises(ValueError):
        ModuleSummary.from_dict({"schema": SUMMARY_SCHEMA})  # no fields
    with pytest.raises(ValueError):
        ModuleSummary.from_dict([])  # not even a dict
    full = {"schema": SUMMARY_SCHEMA, "path": "x.py", "modname": "x",
            "is_package": False, "aliases": {}, "funcs": {},
            "classes": {}, "jit_bindings": {}, "partials": {},
            "local_donations": [], "local_jitted": [],
            "traced_refs": []}
    s = ModuleSummary.from_dict(full)
    assert s.path == "x.py" and s.funcs == {}
    # round-trip: to_dict -> from_dict is identity on the dict form
    assert ModuleSummary.from_dict(s.to_dict()).to_dict() == s.to_dict()
    # dropping any one required field is a schema violation, not a crash
    for k in ("path", "funcs", "aliases"):
        broken = {kk: v for kk, v in full.items() if kk != k}
        with pytest.raises(ValueError):
            ModuleSummary.from_dict(broken)


# --------------------------------------- runtime-evidence bridge (GL013)


def _write_report(run_dir, violations):
    run_dir.mkdir(parents=True, exist_ok=True)
    (run_dir / "sanitize_report.json").write_text(json.dumps(
        {"version": 1, "violations": violations}))


def test_sanitize_report_roundtrip(tmp_path):
    from distributed_pipeline_tpu.utils.perf import (
        SANITIZE_REPORT_NAME, SanitizeReport)

    rep = SanitizeReport()
    rep.record("transfer_guard", detail="boom",
               site={"path": "/a/b.py", "line": 7, "func": "f",
                     "snippet": "x = y"})
    out = rep.write(str(tmp_path))
    assert out.endswith(SANITIZE_REPORT_NAME)
    blob = json.loads((tmp_path / SANITIZE_REPORT_NAME).read_text())
    v = blob["violations"][0]
    assert v["kind"] == "transfer_guard" and v["line"] == 7
    assert v["path"] == "/a/b.py" and v["detail"] == "boom"


def test_sanitize_guard_records_real_trip(tmp_path):
    """A numpy array reaching a jitted call under the guard trips the
    transfer guard; the violation must carry THIS file as its site and
    the exception must still propagate."""
    import numpy as np
    import jax

    from distributed_pipeline_tpu.utils.perf import SanitizeReport

    rep = SanitizeReport(default_dir=str(tmp_path))
    jitted = jax.jit(lambda x: x * 2)
    with pytest.raises(Exception, match="isallow"):
        with rep.guard():
            jitted(np.ones(3))
    assert len(rep.violations) == 1
    v = rep.violations[0]
    assert v["kind"] == "transfer_guard"
    assert v["path"].endswith("test_analysis.py")
    assert (tmp_path / "sanitize_report.json").exists()  # auto-write


def test_runtime_evidence_flags_statically_clean_site(tmp_path, capsys):
    """The acceptance e2e: a planted transfer-guard trip at a site the
    static pass cleared surfaces as GL013 and fails the lint."""
    clean = tmp_path / "clean_mod.py"
    clean.write_text(textwrap.dedent("""
        import jax.numpy as jnp

        def step(x):
            return jnp.sum(x * 2.0)
    """))
    _write_report(tmp_path / "run", [{
        "kind": "transfer_guard", "path": str(clean), "line": 5,
        "func": "step", "snippet": "return jnp.sum(x * 2.0)",
        "detail": "Disallowed host-to-device transfer"}])
    rc = cli_main([str(clean), "--no-cache", "--baseline", "none",
                   "--format", "json",
                   "--runtime-evidence", str(tmp_path / "run")])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    got = [f for f in out["findings"]
           if f["rule"] == "GL013-runtime-coverage-gap"]
    assert len(got) == 1
    assert got[0]["line"] == 5
    assert "transfer" in got[0]["message"]


def test_runtime_evidence_covered_site_is_quiet(tmp_path, capsys):
    """A violation at a line the static pass ALREADY flags is covered —
    the linter told the user; no GL013."""
    dirty = tmp_path / "dirty_mod.py"
    dirty.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def f(x):
            return float(x)
    """))
    fs, _ = run_paths([str(dirty)])
    sync = next(f for f in fs if f.rule == "GL002-host-sync")
    _write_report(tmp_path / "run", [{
        "kind": "transfer_guard", "path": str(dirty), "line": sync.line,
        "func": "f", "snippet": "", "detail": "trip"}])
    rc = cli_main([str(dirty), "--no-cache", "--baseline", "none",
                   "--format", "json",
                   "--runtime-evidence", str(tmp_path / "run")])
    out = json.loads(capsys.readouterr().out)
    assert all(f["rule"] != "GL013-runtime-coverage-gap"
               for f in out["findings"])
    assert rc == 1  # the GL002 itself still fails the lint


def test_runtime_evidence_missing_report_is_usage_error(tmp_path, capsys):
    clean = tmp_path / "mod.py"
    clean.write_text("x = 1\n")
    rc = cli_main([str(clean), "--no-cache", "--baseline", "none",
                   "--runtime-evidence", str(tmp_path / "nowhere")])
    assert rc == 2


def test_note_recompiles_steady_boundary(tmp_path):
    """Compiles up to the steady boundary are warmup; only the ones
    after it become violations, each at its captured user site."""
    import logging

    from distributed_pipeline_tpu.utils.perf import (
        RecompileMonitor, SanitizeReport)

    def compile_record(name):
        return logging.LogRecord(
            "jax", logging.WARNING, __file__, 1,
            f"Compiling {name} because shape changed", None, None)

    mon = RecompileMonitor(capture_sites=True)
    for i in range(3):
        mon.emit(compile_record(f"f{i}"))
    assert mon.count == 3 and len(mon.sites) == 3

    rep = SanitizeReport()
    rep.note_recompiles(mon, steady_after=1)  # first compile = warmup
    assert len(rep.violations) == 2
    assert all(v["kind"] == "steady_recompile" for v in rep.violations)
    assert all(v["path"].endswith("test_analysis.py")
               for v in rep.violations)

    # site-less monitor still leaves (unlocatable) evidence
    bare = RecompileMonitor()
    bare.emit(compile_record("g"))
    bare.emit(compile_record("h"))
    rep2 = SanitizeReport()
    rep2.note_recompiles(bare, steady_after=1)
    assert len(rep2.violations) == 1 and not rep2.violations[0]["path"]
