"""Fixture worker: a REAL multi-process training run over a spawned
jax.distributed ring (run via ``--distributed --nprocs 2``).

Exercises the full multi-host path end-to-end: per-host data sharding,
``make_array_from_process_local_data`` batch assembly, the jitted train step
over a multi-process mesh, cross-process metric averaging, and multi-host
Orbax save/auto-resume.

``--die_at_step K``: process 1 SIGKILLs itself ONCE at step K (a marker file
in the run dir makes the restarted attempt survive) — the fault-injection
half of the launcher's ``--max_restarts`` supervision test.
"""

import argparse
import json
import os
import signal

import distributed_pipeline_tpu.parallel as par

parser = argparse.ArgumentParser()
parser.add_argument("--ckpt_dir", required=True)
parser.add_argument("--steps", type=int, default=6)
parser.add_argument("--save_interval", type=int, default=2)
parser.add_argument("--die_at_step", type=int, default=0)
parser.add_argument("--eval_decode", action="store_true",
                    help="attach the decode eval callback (every process "
                         "joins its jit over the globally-sharded params)")
ns = par.parse_and_autorun(parser)
par.setup_dist()

import jax  # noqa: E402  (after setup_dist, like a real worker)

from distributed_pipeline_tpu.data import load_data_from_args  # noqa: E402
from distributed_pipeline_tpu.models import create_model_from_config  # noqa: E402
from distributed_pipeline_tpu.parallel import make_mesh  # noqa: E402
from distributed_pipeline_tpu.utils import logger  # noqa: E402
from distributed_pipeline_tpu.utils.trainer import TrainLoop  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
rank = jax.process_index()

logger.configure(dir=ns.ckpt_dir, format_strs=["log"],
                 comm=logger.distributed_mean_comm())

batch = 4  # per host -> global 8 (reference trainer.py:89 semantics)
wl = create_model_from_config(
    model_family="diffuseq", vocab_size=64, seq_len=16, hidden_size=32,
    num_layers=1, num_heads=2, diffusion_steps=50, dtype="float32")
data = load_data_from_args("train", batch_size=batch, seq_len=16,
                           vocab_size=64, seed=0)
callbacks = []
if ns.eval_decode:
    from distributed_pipeline_tpu.models.sampling import make_decode_callback

    # host_sharded=False: the decode batch feeds a collective jit as a
    # replicated array, so every host must hold the SAME bytes.
    decode_data = load_data_from_args(
        "valid", batch_size=4, seq_len=16, vocab_size=64, seed=0,
        deterministic=True, host_sharded=False)
    callbacks.append(make_decode_callback(decode_data, sample_steps=4))
loop = TrainLoop(model=wl, data=data, batch_size=batch, microbatch=2,
                 lr=1e-3, ema_rate="0.9", learning_steps=ns.steps,
                 log_interval=10 ** 6, save_interval=ns.save_interval,
                 eval_callbacks=callbacks,
                 mesh=make_mesh(dp=-1), checkpoint_dir=ns.ckpt_dir, seed=0)
assert loop.global_batch == batch * jax.process_count(), loop.global_batch

marker = os.path.join(ns.ckpt_dir, "died.marker")
losses = []
while loop.step < ns.steps:
    if (ns.die_at_step and rank == 1 and loop.step == ns.die_at_step
            and not os.path.exists(marker)):
        with open(marker, "w") as f:
            f.write("x")
        os.kill(os.getpid(), signal.SIGKILL)
    metrics = loop.run_step(next(loop.data))
    losses.append(float(metrics["loss"]))
    if loop.step % loop.save_interval == 0:
        loop.save()

assert all(l == l for l in losses), f"NaN loss: {losses}"
if ns.eval_decode:
    # EVERY process joins the callback (it jits over the globally-sharded
    # params — trainer.run_loop semantics); output is logger-rank-gated.
    from distributed_pipeline_tpu.utils import logger as dpt_logger

    for cb in loop.eval_callbacks:
        cb(loop)
    acc = dpt_logger.getkvs().get("decode_acc")
    print(f"DECODE {rank} {acc}")
if rank == 0:
    with open(os.path.join(ns.ckpt_dir, "trace.json"), "w") as f:
        json.dump({"first_step": ns.steps - len(losses) + 1,
                   "losses": losses}, f)
print(f"TRAINRANK {rank} OK steps={len(losses)} "
      f"first_loss={losses[0]:.4f} last_loss={losses[-1]:.4f}")
