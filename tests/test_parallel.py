"""Distributed substrate tests on the fake 8-device CPU mesh (SURVEY.md §4)."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pipeline_tpu.parallel import (
    batch_spec,
    dist,
    make_mesh,
    resolve_axis_sizes,
)


def test_fake_devices_present():
    assert jax.device_count() == 8


def test_single_process_degradation():
    # Reference contract (SURVEY.md §2.3): every comm primitive no-ops
    # without a cluster.
    assert dist.get_rank() == 0
    assert dist.get_world_size() == 1
    dist.barrier()  # no-op, must not raise
    tree = {"w": jnp.ones((2, 2))}
    out = dist.broadcast(tree)
    assert out is tree
    assert dist.sync_params(tree) is tree
    assert dist.dev() in jax.local_devices()


def test_setup_dist_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    dist.setup_dist.cache_clear()
    dist.setup_dist()  # must not raise or hang
    assert not dist.is_initialized()


def test_find_free_port():
    p = dist.find_free_port()
    assert 1024 < p < 65536


def test_resolve_axis_sizes():
    # Returns sizes in AXES order: (data, fsdp, sequence, tensor).
    assert resolve_axis_sizes(dp=-1, n_devices=8) == (8, 1, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=-1, n_devices=8) == (2, 4, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=2, tensor=2, n_devices=8) == (2, 2, 1, 2)
    assert resolve_axis_sizes(dp=2, fsdp=2, sequence=2, n_devices=8) == (2, 2, 2, 1)
    with pytest.raises(ValueError):
        resolve_axis_sizes(dp=3, n_devices=8)
    with pytest.raises(ValueError):
        resolve_axis_sizes(dp=-1, fsdp=-1, n_devices=8)


@pytest.mark.parametrize("axes", [
    dict(dp=-1), dict(dp=2, fsdp=4), dict(dp=2, fsdp=2, tensor=2),
    dict(dp=1, sequence=8),
])
def test_make_mesh_shapes(axes):
    mesh = make_mesh(**axes)
    assert mesh.devices.size == 8
    assert set(mesh.shape.keys()) == {"data", "fsdp", "sequence", "tensor"}


def test_mesh_psum_rides_sharding():
    # The DDP-replacement property: an all-reduce emitted by XLA from a
    # NamedSharding, no explicit collective call.
    mesh = make_mesh(dp=8)
    x = jnp.arange(16.0).reshape(8, 2)
    sharded = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def global_sum(v):
        return v.sum()

    assert float(global_sum(sharded)) == float(x.sum())


def test_batch_spec():
    mesh = make_mesh(dp=4, fsdp=2)
    assert batch_spec(mesh) == P(("data", "fsdp"))
    mesh_dp = make_mesh(dp=8)
    assert batch_spec(mesh_dp) == P("data")
    mesh_sp = make_mesh(dp=1, sequence=8)
    assert batch_spec(mesh_sp, seq_sharded=True) == P(None, "sequence")


def test_launcher_spawns_real_multiprocess_ring():
    # End-to-end: --distributed --nprocs 2 must give each worker
    # process_count()==2 over a loopback jax.distributed ring
    # (dev-mode stand-in for torchrun --standalone).
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "tests._launcher_child",
         "--distributed", "--nprocs", "2"],
        capture_output=True, text=True, timeout=120, cwd=repo_root,
    )
    assert out.returncode == 0, out.stderr
    assert "RANK 0 OK" in out.stdout and "RANK 1 OK" in out.stdout


def _run_train_child(tmp_path, extra, timeout=420):
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-m", "tests._train_child",
         "--distributed", "--nprocs", "2",
         "--ckpt_dir", str(tmp_path), *extra],
        capture_output=True, text=True, timeout=timeout, cwd=repo_root,
    )


def test_multiprocess_end_to_end_training(tmp_path):
    """VERDICT r1 #4: real TrainLoop steps over a 2-process loopback ring —
    per-host batches assembled into global arrays
    (make_array_from_process_local_data), global_batch = local x hosts,
    multi-host Orbax save."""
    import json
    import os

    out = _run_train_child(tmp_path, ["--steps", "6", "--save_interval", "3"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "TRAINRANK 0 OK" in out.stdout and "TRAINRANK 1 OK" in out.stdout
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["first_step"] == 1 and len(trace["losses"]) == 6
    # Training must actually learn (not just run): loss drops over 6 steps.
    assert trace["losses"][-1] < trace["losses"][0]
    assert (tmp_path / "model_000006").is_dir()  # multi-host Orbax save


def test_launcher_restart_supervision_resumes_past_checkpoint(tmp_path):
    """VERDICT r1 #6: SIGKILL a worker mid-run; with --max_restarts the
    launcher respawns the ring and checkpoint auto-resume continues the job
    past its last checkpoint step (reference torch.elastic --max_restarts,
    dist_run.py:123-136)."""
    import json

    out = _run_train_child(
        tmp_path,
        ["--steps", "6", "--save_interval", "2", "--die_at_step", "3",
         "--max_restarts", "1"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "restart 1/1" in out.stdout
    assert (tmp_path / "died.marker").exists()
    # The restarted attempt resumed from the step-2 checkpoint, not scratch.
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["first_step"] == 3, trace
    assert (tmp_path / "model_000006").is_dir()
