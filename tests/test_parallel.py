"""Distributed substrate tests on the fake 8-device CPU mesh (SURVEY.md §4)."""

import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_pipeline_tpu.parallel import (
    batch_spec,
    dist,
    make_mesh,
    resolve_axis_sizes,
)


def test_fake_devices_present():
    assert jax.device_count() == 8


def test_single_process_degradation():
    # Reference contract (SURVEY.md §2.3): every comm primitive no-ops
    # without a cluster.
    assert dist.get_rank() == 0
    assert dist.get_world_size() == 1
    dist.barrier()  # no-op, must not raise
    tree = {"w": jnp.ones((2, 2))}
    out = dist.broadcast(tree)
    assert out is tree
    assert dist.sync_params(tree) is tree
    assert dist.dev() in jax.local_devices()


def test_setup_dist_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    dist.setup_dist.cache_clear()
    dist.setup_dist()  # must not raise or hang
    assert not dist.is_initialized()


def test_find_free_port():
    p = dist.find_free_port()
    assert 1024 < p < 65536


def test_resolve_axis_sizes():
    # Returns sizes in AXES order: (data, fsdp, sequence, tensor, expert,
    # pipe).
    assert resolve_axis_sizes(dp=-1, n_devices=8) == (8, 1, 1, 1, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=-1, n_devices=8) == (2, 4, 1, 1, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=2, tensor=2, n_devices=8) == (2, 2, 1, 2, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=2, sequence=2, n_devices=8) == (2, 2, 2, 1, 1, 1)
    assert resolve_axis_sizes(dp=2, fsdp=2, expert=2, n_devices=8) == (2, 2, 1, 1, 2, 1)
    assert resolve_axis_sizes(dp=2, pipe=4, n_devices=8) == (2, 1, 1, 1, 1, 4)
    with pytest.raises(ValueError):
        resolve_axis_sizes(dp=3, n_devices=8)
    with pytest.raises(ValueError):
        resolve_axis_sizes(dp=-1, fsdp=-1, n_devices=8)


@pytest.mark.parametrize("axes", [
    dict(dp=-1), dict(dp=2, fsdp=4), dict(dp=2, fsdp=2, tensor=2),
    dict(dp=1, sequence=8),
])
def test_make_mesh_shapes(axes):
    mesh = make_mesh(**axes)
    assert mesh.devices.size == 8
    assert set(mesh.shape.keys()) == {"data", "fsdp", "sequence", "tensor",
                                      "expert", "pipe"}


class _SliceDev:
    """Proxy giving a real device a fake slice_index (multi-slice pods
    can't be simulated on CPU; the hybrid-mesh wiring can)."""

    def __init__(self, d, s):
        self._d = d
        self.slice_index = s

    def __getattr__(self, name):
        return getattr(self._d, name)


def test_multislice_mesh_uses_hybrid(monkeypatch):
    """Devices spanning >1 slice route through create_hybrid_device_mesh
    with data split across DCN and all other axes inside a slice."""
    import numpy as np
    from jax.experimental import mesh_utils

    from distributed_pipeline_tpu.parallel import mesh as mesh_mod

    devs = jax.devices()
    proxies = [_SliceDev(d, i // 4) for i, d in enumerate(devs)]  # 2 slices
    calls = {}

    def fake_hybrid(ici_shape, dcn_shape, devices=None):
        calls["ici"] = tuple(ici_shape)
        calls["dcn"] = tuple(dcn_shape)
        full = tuple(a * b for a, b in zip(dcn_shape, ici_shape))
        return np.array([p._d for p in devices]).reshape(full)

    monkeypatch.setattr(mesh_utils, "create_hybrid_device_mesh", fake_hybrid)
    m = mesh_mod.make_mesh(dp=4, tensor=2, devices=proxies)
    assert calls["dcn"] == (2, 1, 1, 1, 1, 1)       # slices -> data axis
    assert calls["ici"] == (2, 1, 1, 2, 1, 1)       # per-slice dp x tensor
    assert m.shape["data"] == 4 and m.shape["tensor"] == 2

    # dp not divisible by the slice count must fail loudly, not span DCN
    # with a per-layer-collective axis
    with pytest.raises(ValueError, match="data axis"):
        mesh_mod.make_mesh(dp=1, fsdp=4, tensor=2, devices=proxies)


def test_mesh_psum_rides_sharding():
    # The DDP-replacement property: an all-reduce emitted by XLA from a
    # NamedSharding, no explicit collective call.
    mesh = make_mesh(dp=8)
    x = jnp.arange(16.0).reshape(8, 2)
    sharded = jax.device_put(x, NamedSharding(mesh, P("data")))

    @jax.jit
    def global_sum(v):
        return v.sum()

    assert float(global_sum(sharded)) == float(x.sum())


def test_batch_spec():
    mesh = make_mesh(dp=4, fsdp=2)
    assert batch_spec(mesh) == P(("data", "fsdp"))
    mesh_dp = make_mesh(dp=8)
    assert batch_spec(mesh_dp) == P("data")
    mesh_sp = make_mesh(dp=1, sequence=8)
    assert batch_spec(mesh_sp, seq_sharded=True) == P(None, "sequence")


def test_launcher_spawns_real_multiprocess_ring():
    # End-to-end: --distributed --nprocs 2 must give each worker
    # process_count()==2 over a loopback jax.distributed ring
    # (dev-mode stand-in for torchrun --standalone).
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = subprocess.run(
        [sys.executable, "-m", "tests._launcher_child",
         "--distributed", "--nprocs", "2"],
        capture_output=True, text=True, timeout=120, cwd=repo_root,
    )
    assert out.returncode == 0, out.stderr
    assert "RANK 0 OK" in out.stdout and "RANK 1 OK" in out.stdout


def test_launcher_log_dir_captures_per_worker_output(tmp_path):
    """--log_dir routes each worker's stdout+stderr into worker_{i}.log
    (torchrun --log_dir redirects); the parent's stdout then carries only
    launcher lines."""
    import os
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    log_dir = str(tmp_path / "wlogs")
    out = subprocess.run(
        [sys.executable, "-m", "tests._launcher_child",
         "--distributed", "--nprocs", "2", "--log_dir", log_dir],
        capture_output=True, text=True, timeout=120, cwd=repo_root,
    )
    assert out.returncode == 0, out.stderr
    assert "RANK" not in out.stdout  # worker output no longer on the pipe
    logs = {i: open(os.path.join(log_dir, f"worker_{i}.log")).read()
            for i in (0, 1)}
    ranks = {i: next(ln for ln in logs[i].splitlines() if "OK" in ln)
             for i in (0, 1)}
    assert sorted(ranks.values()) == ["RANK 0 OK", "RANK 1 OK"], ranks


def _run_train_child(tmp_path, extra, timeout=420):
    """Run the 2-process training child, retrying ONCE on a nonzero exit:
    the loopback jax.distributed ring's coordinator handshake can time out
    on a heavily loaded machine (observed as a one-off under a full
    parallel suite run) — an infra flake, not a code failure. A genuine
    bug fails both attempts."""
    import os
    import shutil
    import sys as _sys
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    cmd = [sys.executable, "-m", "tests._train_child",
           "--distributed", "--nprocs", "2",
           "--ckpt_dir", str(tmp_path), *extra]

    def attempt_once():
        try:
            return subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=timeout, cwd=repo_root)
        except subprocess.TimeoutExpired as e:
            # a hung handshake is the same flake class as an erroring one
            return subprocess.CompletedProcess(
                cmd, returncode=-1,
                stdout=(e.stdout or b"").decode() if isinstance(
                    e.stdout, bytes) else (e.stdout or ""),
                stderr=f"TimeoutExpired after {timeout}s")

    out = attempt_once()
    if out.returncode != 0:
        # LOUD retry: a recurring failure here is signal (a flaky product
        # race would otherwise hide behind silent retries)
        print(f"_run_train_child: attempt 0 failed rc={out.returncode}; "
              f"stderr tail: {out.stderr[-500:]!r}; retrying once",
              file=_sys.stderr, flush=True)
        # wipe the failed attempt's partial state (checkpoints, markers) so
        # the retry is a genuinely fresh run, not an accidental resume
        for child in tmp_path.iterdir():
            if child.is_dir():
                shutil.rmtree(child, ignore_errors=True)
            else:
                child.unlink(missing_ok=True)
        out = attempt_once()
    return out


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_multiprocess_end_to_end_training(tmp_path):
    """VERDICT r1 #4: real TrainLoop steps over a 2-process loopback ring —
    per-host batches assembled into global arrays
    (make_array_from_process_local_data), global_batch = local x hosts,
    multi-host Orbax save."""
    import json
    import os

    out = _run_train_child(tmp_path, ["--steps", "6", "--save_interval", "3"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "TRAINRANK 0 OK" in out.stdout and "TRAINRANK 1 OK" in out.stdout
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["first_step"] == 1 and len(trace["losses"]) == 6
    # Training must actually learn (not just run): loss drops over 6 steps.
    assert trace["losses"][-1] < trace["losses"][0]
    assert (tmp_path / "model_000006").is_dir()  # multi-host Orbax save


def test_resolve_run_dir_uses_pinned_timestamp(monkeypatch):
    """ADVICE r2 medium: restart supervision only works if every attempt
    resolves the SAME auto-generated run dir — the launcher pins
    DPT_RUN_TIMESTAMP and run/train derives the dir from it."""
    from distributed_pipeline_tpu.config.train import TrainSettings
    from distributed_pipeline_tpu.run.train import resolve_run_dir

    args = TrainSettings()
    monkeypatch.setenv("DPT_RUN_TIMESTAMP", "19990101-000000")
    d1, d2 = resolve_run_dir(args), resolve_run_dir(args)
    assert d1 == d2 and d1.endswith("19990101-000000")
    # explicit --checkpoint_path always wins
    explicit = TrainSettings(checkpoint_path="/x/y")
    assert resolve_run_dir(explicit) == "/x/y"


def test_launcher_pins_timestamp_across_attempts(monkeypatch):
    """run_argv_as_distributed must hand every attempt's workers the SAME
    DPT_RUN_TIMESTAMP (so respawned rings resolve the same run dir) WITHOUT
    mutating this process's environ (a second launch from the same process
    must mint a fresh timestamp, not resume run 1's checkpoints)."""
    import os

    from distributed_pipeline_tpu.parallel import launcher

    from tests._fake_ring import make_fake_ring

    monkeypatch.delenv("DPT_RUN_TIMESTAMP", raising=False)
    fake = make_fake_ring(codes=(1, 0))  # fail once, then succeed
    monkeypatch.setattr(launcher, "_run_worker_ring", fake)
    code = launcher.run_argv_as_distributed("mod", [], nprocs=2,
                                            max_restarts=3,
                                            restart_backoff_s=0.01)
    assert code == 0
    seen = [c["run_timestamp"] for c in fake.calls]
    assert len(seen) == 2 and seen[0] is not None and seen[0] == seen[1]
    assert "DPT_RUN_TIMESTAMP" not in os.environ  # no process-global leak


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_launcher_restart_supervision_resumes_past_checkpoint(tmp_path):
    """VERDICT r1 #6: SIGKILL a worker mid-run; with --max_restarts the
    launcher respawns the ring and checkpoint auto-resume continues the job
    past its last checkpoint step (reference torch.elastic --max_restarts,
    dist_run.py:123-136)."""
    import json

    out = _run_train_child(
        tmp_path,
        ["--steps", "6", "--save_interval", "2", "--die_at_step", "3",
         "--max_restarts", "1"])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "restart 1/1" in out.stdout
    assert (tmp_path / "died.marker").exists()
    # The restarted attempt resumed from the step-2 checkpoint, not scratch.
    trace = json.loads((tmp_path / "trace.json").read_text())
    assert trace["first_step"] == 3, trace
    assert (tmp_path / "model_000006").is_dir()


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_multiprocess_decode_callback(tmp_path):
    """The eval-decode callback jits over globally-sharded params, so EVERY
    process must join it (code-review r3 finding): a 2-process ring runs the
    callback on both ranks and they agree on the metric."""
    out = _run_train_child(tmp_path, ["--steps", "2", "--save_interval", "5",
                                      "--eval_decode"])
    assert out.returncode == 0, out.stderr[-2000:]
    vals = dict(line.split()[1:3] for line in out.stdout.splitlines()
                if line.startswith("DECODE "))
    assert set(vals) == {"0", "1"}, out.stdout
    assert vals["0"] == vals["1"] != "None"


def test_launcher_log_tee(tmp_path, capfd):
    """--log_tee (torchrun -t tee): each worker's output reaches BOTH its
    log file and the launcher console, '[worker N]'-prefixed."""
    import sys

    from distributed_pipeline_tpu.parallel.launcher import _run_worker_ring

    code = _run_worker_ring(
        [sys.executable, "-c", "print('tee-marker-xyz')"],
        nprocs=2, devices_per_proc=1, monitor_interval=0.05,
        log_dir=str(tmp_path), log_tee=True)
    assert code == 0
    out, _ = capfd.readouterr()
    # the cmdline echo also contains the marker; count teed WORKER lines
    assert out.count("] tee-marker-xyz") == 2
    assert "[worker 0]" in out and "[worker 1]" in out
    for i in range(2):
        assert "tee-marker-xyz" in (tmp_path / f"worker_{i}.log").read_text()
