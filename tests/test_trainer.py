"""TrainLoop tests: the jitted-step engine, sharding, checkpoint/resume.

Covers the reference-parity semantics SURVEY.md §4 lists as test-worthy:
EMA math (trainer.py:360-370), LR anneal (:257-263), grad clip (:246-255),
microbatch accumulation equivalence (:230-235), checkpoint filename
convention and auto-resume (:319-355) — all on a real 8-device mesh.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_pipeline_tpu.data import load_data_from_args
from distributed_pipeline_tpu.models import create_model_from_config
from distributed_pipeline_tpu.parallel import make_mesh
from distributed_pipeline_tpu.parallel.sharding import (
    batch_shardings,
    param_shardings,
    shard_batch,
)
from distributed_pipeline_tpu.utils import checkpoint as ckpt
from distributed_pipeline_tpu.utils import logger
from distributed_pipeline_tpu.utils.trainer import TrainLoop, update_ema


def tiny_workload(fam="gpt2", seq_len=16):
    return create_model_from_config(
        model_family=fam, vocab_size=64, seq_len=seq_len, hidden_size=32,
        num_layers=2, num_heads=2, diffusion_steps=50, dtype="float32")


def tiny_data(fam="gpt2", batch_size=8, seq_len=16, seed=0):
    name = "synthetic-lm" if fam == "gpt2" else "synthetic-seq2seq"
    return load_data_from_args("train", batch_size=batch_size, dataset=name,
                               seq_len=seq_len, vocab_size=64, seed=seed)


def make_loop(tmp_path, fam="gpt2", **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("lr", 1e-3)
    kw.setdefault("learning_steps", 1000)
    kw.setdefault("log_interval", 1000)
    kw.setdefault("save_interval", 10 ** 9)
    kw.setdefault("mesh", make_mesh(dp=8))
    kw.setdefault("ema_rate", "0.9")
    kw.setdefault("seed", 5)
    data = kw.pop("data", None) or tiny_data(fam, kw["batch_size"])
    return TrainLoop(model=tiny_workload(fam), data=data,
                     checkpoint_dir=str(tmp_path), **kw)


# --------------------------------------------------------------- core engine

def test_loss_decreases_over_steps(tmp_path):
    loop = make_loop(tmp_path)
    first = float(loop.run_step(next(loop.data))["loss"])
    for _ in range(30):
        m = loop.run_step(next(loop.data))
    assert float(m["loss"]) < first
    assert loop.step == 31


def test_loss_decreases_with_prefetch_and_lagged_dispatch(tmp_path):
    """The non-eager TrainLoop shipped as the CONFIG default (PR 5:
    prefetch_depth=2, dispatch_lag=1) must train like the eager path —
    tier-1 exercises the real-run configuration, not just the wrapper's
    own unit tests (test_device_prefetch.py)."""
    loop = make_loop(tmp_path, prefetch_depth=2, dispatch_lag=1)
    first = float(loop.run_step(next(loop.data))["loss"])  # DeviceBatch path
    for _ in range(30):
        m = loop.run_step(next(loop.data))
    loop.flush_metrics()  # drain the lagged ring like run_loop's boundaries
    assert float(m["loss"]) < first
    assert loop.step == 31


def test_grad_accumulation_equivalence(tmp_path):
    """microbatch=B vs microbatch=B/4 must produce identical updates for an
    rng-independent loss (the reference's no_sync accumulation semantics)."""
    batches = [next(tiny_data("gpt2", 8, seed=1)) for _ in range(2)]
    results = []
    for mb in (8, 2):
        it = iter(batches)
        loop = make_loop(tmp_path / f"mb{mb}", microbatch=mb, data=it,
                         mesh=make_mesh(dp=2, fsdp=1, tensor=1, sequence=1,
                                        devices=jax.devices()[:2]))
        for b in batches:
            loop.run_step(b)
        results.append(jax.tree_util.tree_leaves(loop.state.params))
    for a, b in zip(*results):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_lr_anneal_linear(tmp_path):
    loop = make_loop(tmp_path, lr=1e-2, learning_steps=100)
    m = loop.run_step(next(loop.data))
    # step 0 metric: lr * (1 - 0/100)
    np.testing.assert_allclose(float(m["lr"]), 1e-2, rtol=1e-6)
    for _ in range(9):
        m = loop.run_step(next(loop.data))
    np.testing.assert_allclose(float(m["lr"]), 1e-2 * (1 - 9 / 100), rtol=1e-5)


def test_grad_clip_changes_update_and_logs_preclip_norm(tmp_path):
    """Clip rescales grads before Adam (reference grad_clip trainer.py:
    246-255); the logged norm is the pre-clip norm. (Adam is scale-invariant
    in the long run but a one-step update still differs under clipping.)"""
    batch = next(tiny_data("gpt2", 8, seed=4))
    outs = {}
    for clip in (-1.0, 1e-3):
        loop = make_loop(tmp_path / f"clip{clip}", gradient_clipping=clip,
                         data=iter([batch]))
        m = loop.run_step(batch)
        outs[clip] = (float(m["grad_norm"]),
                      jax.tree_util.tree_leaves(loop.state.params))
    # same pre-clip grad norm logged in both runs
    np.testing.assert_allclose(outs[-1.0][0], outs[1e-3][0], rtol=1e-5)
    assert outs[-1.0][0] > 1e-3  # clip threshold actually binds
    diffs = [np.abs(np.asarray(a) - np.asarray(b)).max()
             for a, b in zip(outs[-1.0][1], outs[1e-3][1])]
    assert max(diffs) > 1e-6  # clipping altered the first-step update


def test_ema_update_math():
    ema = {"w": jnp.ones((4,))}
    params = {"w": jnp.zeros((4,))}
    out = update_ema(ema, params, 0.9)
    np.testing.assert_allclose(np.asarray(out["w"]), 0.9)


def test_ema_tracks_params(tmp_path):
    loop = make_loop(tmp_path, ema_rate="0.5,0.99")
    for _ in range(5):
        loop.run_step(next(loop.data))
    p = jax.tree_util.tree_leaves(loop.state.params)
    fast = jax.tree_util.tree_leaves(loop.state.ema["0.5"])
    slow = jax.tree_util.tree_leaves(loop.state.ema["0.99"])
    dist_fast = sum(float(jnp.sum((a - b) ** 2)) for a, b in zip(p, fast))
    dist_slow = sum(float(jnp.sum((a - b) ** 2)) for a, b in zip(p, slow))
    assert 0 < dist_fast < dist_slow  # fast EMA hugs params closer


def test_microbatch_validation(tmp_path):
    with pytest.raises(ValueError):
        make_loop(tmp_path, batch_size=8, microbatch=3)


def test_eval_step_and_metrics(tmp_path):
    loop = make_loop(tmp_path)
    m = loop.forward_only(next(loop.data))
    assert "loss" in m and np.isfinite(float(m["loss"]))


# ----------------------------------------------------------------- sharding

def test_params_are_fsdp_sharded(tmp_path):
    mesh = make_mesh(dp=2, fsdp=4)
    loop = make_loop(tmp_path, mesh=mesh)
    flat = jax.tree_util.tree_leaves_with_path(loop.state.params)
    sharded = [
        (jax.tree_util.keystr(p), l.sharding.spec)
        for p, l in flat
        if any(ax == "fsdp" or (isinstance(ax, tuple) and "fsdp" in ax)
               for ax in (l.sharding.spec or ()))
    ]
    assert sharded, "no parameter was sharded over the fsdp axis"
    # optimizer mu/nu must shard like params (ZeRO memory contract)
    mu_leaves = jax.tree_util.tree_leaves(loop.state.opt_state[0].mu)
    p_leaves = jax.tree_util.tree_leaves(loop.state.params)
    for m, p in zip(mu_leaves, p_leaves):
        assert m.sharding == p.sharding


@pytest.mark.parametrize("axes", [dict(dp=2, fsdp=2, tensor=2),
                                  dict(dp=1, fsdp=1, tensor=8)])
def test_train_step_runs_on_mixed_mesh(tmp_path, axes):
    """DP x FSDP x TP and pure-TP meshes compile and run the same engine
    (strategy = sharding spec, no new code — SURVEY.md §2.2 payoff)."""
    mesh = make_mesh(**axes)
    loop = make_loop(tmp_path / "mixed", mesh=mesh, batch_size=8, microbatch=4)
    m1 = loop.run_step(next(loop.data))
    m2 = loop.run_step(next(loop.data))
    assert np.isfinite(float(m2["loss"]))


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_dp_invariance_across_meshes(tmp_path):
    """The same data must give the same loss no matter how it is sharded."""
    batches = [next(tiny_data("gpt2", 8, seed=9)) for _ in range(1)]
    losses = []
    for axes in (dict(dp=8), dict(dp=2, fsdp=4), dict(dp=4, tensor=2)):
        loop = make_loop(tmp_path / str(axes), mesh=make_mesh(**axes),
                         data=iter(batches))
        losses.append(float(loop.run_step(batches[0])["loss"]))
    np.testing.assert_allclose(losses[0], losses[1], rtol=1e-5)
    np.testing.assert_allclose(losses[0], losses[2], rtol=1e-5)


def test_shard_batch_layout():
    mesh = make_mesh(dp=8)
    b = {"x": np.arange(64, dtype=np.int32).reshape(8, 8)}
    g = shard_batch(mesh, b)
    assert g["x"].shape == (8, 8)
    assert g["x"].sharding.spec == batch_shardings(mesh).spec


# ------------------------------------------------------------- checkpointing

def test_parse_step_from_name():
    assert ckpt.parse_step_from_name("model_012345") == 12345
    assert ckpt.parse_step_from_name("ema_0.99_000020") == 20
    assert ckpt.parse_step_from_name("model_") is None


def test_checkpoint_roundtrip_and_discovery(tmp_path):
    d = str(tmp_path)
    tree = {"a": jnp.arange(8.0), "b": {"c": jnp.ones((2, 2))}}
    ckpt.save_checkpoint(d, 7, tree)
    ckpt.save_checkpoint(d, 20, jax.tree_util.tree_map(lambda x: x * 2, tree))
    assert ckpt.latest_step(d) == 20
    assert ckpt.find_resume_checkpoint(d).endswith("model_000020")
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), tree)
    restored = ckpt.restore_checkpoint(os.path.join(d, "model_000007"),
                                       abstract)
    np.testing.assert_allclose(np.asarray(restored["a"]),
                               np.arange(8.0))


def test_resume_continues_training(tmp_path):
    loop = make_loop(tmp_path, save_interval=10 ** 9)
    for _ in range(3):
        loop.run_step(next(loop.data))
    loop.save()
    # new loop in the same dir auto-discovers and resumes
    loop2 = make_loop(tmp_path)
    assert loop2.step == 3
    assert int(loop2.state.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(loop.state.params),
                    jax.tree_util.tree_leaves(loop2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    # EMA survived too
    for a, b in zip(jax.tree_util.tree_leaves(loop.state.ema["0.9"]),
                    jax.tree_util.tree_leaves(loop2.state.ema["0.9"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))
    m = loop2.run_step(next(loop2.data))
    assert loop2.step == 4 and np.isfinite(float(m["loss"]))


def test_resume_across_mesh_change(tmp_path):
    """Checkpoints are topology-independent: save on dp=8, resume on
    dp=2 x fsdp=4 (elastic-recovery story, SURVEY.md §5.3)."""
    loop = make_loop(tmp_path, mesh=make_mesh(dp=8))
    loop.run_step(next(loop.data))
    loop.save()
    loop2 = make_loop(tmp_path, mesh=make_mesh(dp=2, fsdp=4))
    assert loop2.step == 1
    for a, b in zip(jax.tree_util.tree_leaves(loop.state.params),
                    jax.tree_util.tree_leaves(loop2.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_explicit_resume_path_invalid_raises(tmp_path):
    """ADVICE r1 (medium): a typo'd --resume_checkpoint must fail loudly,
    never silently restart from scratch."""
    from distributed_pipeline_tpu.utils import checkpoint as ckpt_lib

    with pytest.raises(FileNotFoundError):
        ckpt_lib.restore_resume_state(
            str(tmp_path), abstract_params={},
            explicit_model_path=str(tmp_path / "model_000123.pt"))


def test_checkpoint_discovery_through_epath(tmp_path):
    """Discovery/save/resume drive through etils.epath so remote URIs
    (gs://...) take the same code path as local dirs (SURVEY.md §5.4)."""
    from etils import epath

    from distributed_pipeline_tpu.utils import checkpoint as ckpt_lib

    d = epath.Path(str(tmp_path))  # epath-style handle over a local dir
    params = {"w": jnp.arange(4, dtype=jnp.float32)}
    ckpt_lib.save_checkpoint(os.fspath(d), 7, params)
    found = ckpt_lib.find_resume_checkpoint(os.fspath(d))
    assert found is not None and found.endswith("model_000007")
    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), params)
    out = ckpt_lib.restore_resume_state(os.fspath(d), abstract_params=abstract)
    assert out is not None and out["step"] == 7
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.arange(4, dtype=np.float32))


# ------------------------------------------------- debug/profiling flag wiring

def _profile_files(d):
    return [os.path.join(r, f) for r, _, fs in os.walk(d) for f in fs]


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_profile_dir_writes_trace(tmp_path):
    """VERDICT r2 weak #5: --profile_dir captures a jax.profiler trace window
    (steps 3..8 after loop entry) into the directory."""
    trace_dir = tmp_path / "trace"
    loop = make_loop(tmp_path, learning_steps=10,
                     profile_dir=str(trace_dir))
    loop.run_loop()
    assert loop.step == 10 and not loop._profiling
    assert _profile_files(trace_dir), "no trace files written"


def test_profile_run_shorter_than_window(tmp_path):
    """A run that ends INSIDE the profiler window must still stop the trace
    (the run_loop finally clause) and flush files."""
    trace_dir = tmp_path / "trace"
    loop = make_loop(tmp_path, learning_steps=5,
                     profile_dir=str(trace_dir))
    loop.run_loop()  # window is (3, 8): started at 3, run ends at 5
    assert loop.step == 5 and not loop._profiling
    assert _profile_files(trace_dir), "interrupted trace was not flushed"


def test_debug_nans_flag_fails_loudly(tmp_path):
    """VERDICT r2 weak #5: --debug_nans must turn a NaN into a loud
    FloatingPointError at the op that produced it (SURVEY.md §5.2), wired
    through the real run/train.py main()."""
    from distributed_pipeline_tpu.run import train as run_train

    argv = ["--debug_nans", "true", "--lr", "1e38",  # lr overflow -> NaN
            "--batch_size", "8", "--microbatch", "8",
            "--learning_steps", "4", "--log_interval", "1000000",
            "--eval_interval", "1000000", "--save_interval", "1000000",
            "--vocab_size", "64", "--seq_len", "16", "--hidden_size", "32",
            "--num_layers", "1", "--num_heads", "2",
            "--diffusion_steps", "50", "--dtype", "float32",
            "--checkpoint_path", str(tmp_path / "run")]
    ns = run_train.create_parser().parse_args(argv)
    try:
        with pytest.raises(FloatingPointError):
            run_train.main(ns)
    finally:
        jax.config.update("jax_debug_nans", False)


def test_lr_warmup_schedule(tmp_path):
    """--warmup_steps ramps LR linearly before the reference anneal;
    warmup_steps=0 reproduces the reference schedule exactly."""
    loop = make_loop(tmp_path, lr=1e-3, learning_steps=100)
    assert np.isclose(float(loop._lr_at(0)), 1e-3)
    assert np.isclose(float(loop._lr_at(50)), 5e-4)

    loop_w = make_loop(tmp_path / "w", lr=1e-3, learning_steps=100,
                       warmup_steps=10)
    assert np.isclose(float(loop_w._lr_at(0)), 1e-3 * (1 / 10))
    assert np.isclose(float(loop_w._lr_at(4)), 1e-3 * (5 / 10) * 0.96)
    # past warmup: anneal only
    assert np.isclose(float(loop_w._lr_at(50)), 5e-4)
    # and the jitted step consumes it without recompilation issues
    m = loop_w.run_step(next(loop_w.data))
    assert np.isclose(float(m["lr"]), 1e-3 * (1 / 10) * 1.0, rtol=1e-3)


def test_async_save_overlaps_training(tmp_path):
    """save(wait=False) — the run_loop path — schedules the write and
    returns; training steps proceed while it is in flight, and the bytes
    that land are the state AT SAVE TIME, not the mutated-by-later-steps
    state (Orbax's synchronous device-to-host fetch is what makes the
    jitted step's buffer donation safe)."""
    loop = make_loop(tmp_path, save_interval=10 ** 9)
    for _ in range(2):
        loop.run_step(next(loop.data))
    snapshot = jax.tree_util.tree_map(lambda x: np.asarray(x).copy(),
                                      loop.state.params)
    loop.save(wait=False)
    for _ in range(3):  # training proceeds; params diverge from snapshot
        m = loop.run_step(next(loop.data))
    assert np.isfinite(float(m["loss"]))
    loop.wait_for_saves()
    abstract = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), snapshot)
    restored = ckpt.restore_checkpoint(
        os.path.join(str(tmp_path), "model_000002"), abstract)
    for a, b in zip(jax.tree_util.tree_leaves(snapshot),
                    jax.tree_util.tree_leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the post-save steps really moved the live params
    moved = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(snapshot),
                        jax.tree_util.tree_leaves(loop.state.params)))
    assert moved


def test_keep_checkpoints_prunes_old_steps(tmp_path):
    """--keep_checkpoints N retains only the newest N steps, pruning
    model+EMA+opt together; 0 keeps everything (reference behavior)."""
    loop = make_loop(tmp_path, keep_checkpoints=2, save_interval=10 ** 9)
    for _ in range(3):
        loop.run_step(next(loop.data))
        loop.save()
    names = sorted(p.name for p in tmp_path.iterdir())
    assert [n for n in names if n.startswith("model_")] == [
        "model_000002", "model_000003"]
    assert not any(n.endswith("000001") for n in names), names
    # companions of surviving steps intact
    assert any(n.startswith("ema_") and n.endswith("000003") for n in names)
    assert "opt_000003" in names

    # keep_checkpoints=0: nothing pruned
    loop0 = make_loop(tmp_path / "all", keep_checkpoints=0,
                      save_interval=10 ** 9)
    for _ in range(3):
        loop0.run_step(next(loop0.data))
        loop0.save()
    names0 = [p.name for p in (tmp_path / "all").iterdir()
              if p.name.startswith("model_")]
    assert len(names0) == 3


def test_constant_lr_optstate_resumes(tmp_path):
    """Constant-LR runs (learning_steps=0) must keep the plain-float optax
    schedule so their opt_state pytree structure stays restorable."""
    loop = make_loop(tmp_path, learning_steps=0, save_interval=10 ** 9)
    loop.run_step(next(loop.data))
    loop.save()
    loop2 = make_loop(tmp_path, learning_steps=0)
    assert loop2.step == 1
    m = loop2.run_step(next(loop2.data))
    assert np.isfinite(float(m["loss"]))
    assert np.isclose(float(m["lr"]), loop2.lr)


def test_unfinalized_orbax_tmp_ignored(tmp_path):
    """A crash mid-save leaves 'model_NNNNNN.orbax-checkpoint-tmp-<ts>';
    its trailing timestamp must NOT rank as a step — neither for resume
    discovery nor for retention pruning (which would otherwise delete real
    checkpoints and keep the corrupt tmp)."""
    d = str(tmp_path)
    tree = {"a": jnp.arange(4.0)}
    ckpt.save_checkpoint(d, 1, tree)
    ckpt.save_checkpoint(d, 2, tree)
    (tmp_path / "model_000003.orbax-checkpoint-tmp-1712345678901234").mkdir()

    assert ckpt.latest_step(d) == 2
    assert ckpt.find_resume_checkpoint(d).endswith("model_000002")

    pruned = ckpt.prune_checkpoints(d, keep=2)
    assert pruned == []  # two real steps, both kept; tmp didn't count
    ckpt.save_checkpoint(d, 4, tree)
    pruned = ckpt.prune_checkpoints(d, keep=2)
    assert pruned == [1]
    names = {p.name for p in tmp_path.iterdir()}
    assert "model_000002" in names and "model_000004" in names
    # the in-flight/corrupt tmp is left alone
    assert "model_000003.orbax-checkpoint-tmp-1712345678901234" in names


def test_resume_eval_stream_exact_with_changed_interval(tmp_path):
    """VERDICT r4 weak #7: the consumed-eval-batch count is persisted in
    each checkpoint's meta sidecar, so a resume fast-forwards the eval
    stream EXACTLY even when --eval_interval changed between runs (the
    old flag-derived division would replay/skip eval batches)."""
    import json
    import subprocess
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # the conftest's 8-fake-device XLA_FLAGS must not leak into the child:
    # this config's microbatch 4 assumes the default single-device CPU
    env.pop("XLA_FLAGS", None)

    def run(steps, eval_interval):
        cfg = {
            "model_family": "gpt2", "vocab_size": 64, "seq_len": 16,
            "hidden_size": 32, "num_layers": 2, "num_heads": 2,
            "dtype": "float32", "batch_size": 4, "microbatch": 4,
            "lr": 1e-3, "learning_steps": steps, "log_interval": 10 ** 6,
            "save_interval": 4, "eval_interval": eval_interval,
            "dataset": "synthetic-lm",
            "checkpoint_path": str(tmp_path / "run"),
        }
        cfg_path = tmp_path / "cfg.json"
        cfg_path.write_text(json.dumps(cfg))
        out = subprocess.run(
            [sys.executable, "-m", "distributed_pipeline_tpu.run.train",
             "--config_json", str(cfg_path)],
            capture_output=True, text=True, timeout=300, cwd=repo_root,
            env=env)
        assert out.returncode == 0, out.stderr[-2000:]
        return out

    run(4, 2)  # evals at steps 2, 4 -> 2 eval batches consumed
    meta = json.loads((tmp_path / "run" / "meta_000004.json").read_text())
    assert meta["eval_batches_consumed"] == 2
    assert meta["eval_interval"] == 2

    # resume with a DIFFERENT interval: the meta count (2), not
    # resume_step // new_interval (4), must drive the fast-forward
    out = run(8, 1)
    assert "fast-forwarding data stream past 4 consumed train batches / " \
           "2 eval batches" in (out.stdout + out.stderr)
    meta = json.loads((tmp_path / "run" / "meta_000008.json").read_text())
    # resumed at 2 consumed + evals at steps 5,6,7,8 with interval 1
    assert meta["eval_batches_consumed"] == 6


@pytest.mark.slow  # heaviest tier: compile-dominated / multi-loop composition (VERDICT r5 weak #3)
def test_zero_intervals_disable_periodic_actions(tmp_path):
    """Interval <= 0 disables the periodic action instead of dying on the
    modulo (the reference's loop would ZeroDivisionError); the final save
    still runs so the run leaves a restorable checkpoint."""
    import os

    loop = make_loop(tmp_path, learning_steps=3, log_interval=0,
                     save_interval=0)
    loop.run_loop()
    assert loop.step == 3
    saved = sorted(d for d in os.listdir(tmp_path) if d.startswith("model_"))
    assert saved == ["model_000003"]  # exit save only, no periodic saves


# ---------------------------------------------------------- sanitizer mode

def test_sanitize_mode_counts_compiles_and_guards_transfers(tmp_path):
    """--sanitize (the runtime half of graftlint): recompile_count freezes
    once the step functions are built — growth across steady-state steps
    is exactly the silent-retrace regression the gauge exists to catch —
    and the step dispatch runs under a transfer guard that rejects
    implicit host->device transfers while the loop's own explicit
    device_put path keeps working."""
    loop = make_loop(tmp_path, sanitize=True)
    try:
        loop.run_step(next(loop.data))
        after_first = loop.recompile_count
        assert after_first >= 1  # init + train_step compiles were observed
        for _ in range(3):
            loop.run_step(next(loop.data))
        assert loop.step == 4
        assert loop.recompile_count == after_first  # steady state: frozen
        with logger.scoped_configure(dir=str(tmp_path / "l"),
                                     format_strs=["json"]):
            loop.log_step()
            assert logger.dumpkvs()["recompile_count"] == after_first

        # the guard really is armed: an implicit np->device transfer
        # inside the guarded region must raise, not silently transfer
        f = jax.jit(lambda x: x * 2)
        with pytest.raises(Exception, match="[Dd]isallow"):
            with loop._sanitize_guard():
                f(np.ones(3)).block_until_ready()

        # the monitor is still live outside the guard: a deliberate fresh
        # compile (distinctive constants so no cache can satisfy it) must
        # be counted
        g = jax.jit(lambda x: x * 3.14159 + 2.71828)
        g(jnp.ones(3)).block_until_ready()
        assert loop.recompile_count > after_first
        live = loop.recompile_count
    finally:
        final = loop.stop_sanitizer()
    assert final == live  # stop returns the count at detach time
    # and counting really stops once detached
    h = jax.jit(lambda x: x * 1.41421 - 0.57721)
    h(jnp.ones(3)).block_until_ready()
    assert loop.recompile_count == final
    loop.stop_sanitizer()  # idempotent


def test_sanitize_off_by_default(tmp_path):
    loop = make_loop(tmp_path)
    loop.run_step(next(loop.data))
    assert not loop.sanitize and loop.recompile_count == 0
    # steady-state knobs are opt-in at the TrainLoop API level (the
    # config layer turns them on for real runs)
    assert loop.prefetch_depth == 0 and loop.dispatch_lag == 0


def test_sanitize_covers_callbacks_and_checkpoint_roundtrip(tmp_path):
    """ISSUE 5 satellite (ROADMAP open item): the --sanitize transfer
    guard extends beyond step dispatch to eval callbacks and checkpoint
    save/restore. A guard-legal callback (explicit device_get) runs
    fine, saves scheduled under the guard land, a sanitized resume
    restores them — and an IMPLICIT transfer inside a callback raises
    instead of silently serializing the loop."""
    seen = {"n": 0}

    def cb(tl):
        seen["n"] += 1
        assert int(jax.device_get(tl.state.step)) == tl.step  # explicit: ok
        if seen["n"] == 2:
            # implicit host->device transfer: the guard must catch it
            jax.jit(lambda x: x + 1)(np.ones(3))

    loop = make_loop(tmp_path, learning_steps=2, eval_interval=1,
                     save_interval=1, sanitize=True,
                     eval_data=tiny_data("gpt2", 8, seed=6),
                     eval_callbacks=[cb])
    try:
        with pytest.raises(Exception, match="[Dd]isallow"):
            loop.run_loop()
    finally:
        loop.stop_sanitizer()
    assert seen["n"] == 2  # first (legal) callback ran; second tripped

    # step 1's save was scheduled UNDER the guard and still landed —
    # Orbax's device->host fetch is explicit, so sanitized saves work
    assert (tmp_path / "model_000001").is_dir()

    # restore path under the guard: a sanitized loop resumes the
    # guarded-save checkpoint without tripping
    loop2 = make_loop(tmp_path, sanitize=True)
    try:
        assert loop2.step == 1
        m = loop2.run_step(next(loop2.data))
        assert np.isfinite(float(jax.device_get(m["loss"])))
    finally:
        loop2.stop_sanitizer()


def test_shipped_decode_callback_is_guard_clean(tmp_path):
    """Code-review regression: make_decode_callback used to build its
    PRNGKey eagerly in-call and dispatch off-mesh args, tripping the
    --sanitize transfer guard the moment eval callbacks ran under it.
    The shipped callback must run guard-clean — diffuseq specifically,
    because its sampler CONSUMES the rng (gpt2's jit prunes the unused
    key arg, hiding the off-mesh reshard)."""
    from distributed_pipeline_tpu.models.sampling import make_decode_callback

    data = load_data_from_args("valid", batch_size=8,
                               dataset="synthetic-seq2seq", seq_len=16,
                               vocab_size=64, seed=0, deterministic=True)
    cb = make_decode_callback(data, sample_steps=3)
    loop = make_loop(tmp_path, fam="diffuseq", learning_steps=2,
                     eval_interval=1, sanitize=True,
                     eval_data=tiny_data("diffuseq", 8, seed=6),
                     eval_callbacks=[cb])
    try:
        with logger.scoped_configure(dir=str(tmp_path / "logs"),
                                     format_strs=["json"]):
            loop.run_loop()  # would raise Disallowed...transfer pre-fix
            d = logger.dumpkvs()
        assert 0.0 <= d["decode_acc"] <= 1.0
    finally:
        loop.stop_sanitizer()
