"""Config bridge tests (SURVEY.md §4 recommends: argparse<->json<->pydantic
round-trip semantics of the reference config/base.py)."""

import argparse
import json
from typing import Literal

import pytest

from distributed_pipeline_tpu.config import (
    ArgparseCompatibleBaseModel as S,
    TrainSettings,
    item,
)


class Inner(S):
    alpha: float = item(0.5, "inner alpha")
    kind: Literal["a", "b"] = item("a", "inner kind")


class Demo(S):
    lr: float = item(1e-4, "learning rate")
    steps: int = item(100)
    use_ema: bool = item(True)
    name: str = item("demo")
    inner: Inner = Inner()


def test_to_argparse_defaults():
    ns = Demo.to_argparse().parse_args([])
    cfg = Demo.from_argparse(ns)
    assert cfg == Demo()


def test_cli_overrides_and_nested_group():
    ns = Demo.to_argparse().parse_args(
        ["--lr", "3e-4", "--alpha", "0.9", "--kind", "b", "--use_ema", "false"]
    )
    cfg = Demo.from_argparse(ns)
    assert cfg.lr == 3e-4
    assert cfg.inner.alpha == 0.9
    assert cfg.inner.kind == "b"
    assert cfg.use_ema is False


@pytest.mark.parametrize("val,expect", [("true", True), ("0", False), ("YES", True)])
def test_bool_coercion(val, expect):
    ns = Demo.to_argparse().parse_args(["--use_ema", val])
    assert Demo.from_argparse(ns).use_ema is expect


def test_literal_choices_rejected():
    with pytest.raises(SystemExit):
        Demo.to_argparse().parse_args(["--kind", "zzz"])


def test_leftover_keys_rejected():
    # Reference asserts no unconsumed namespace keys (config/base.py:30).
    ns = argparse.Namespace(lr=1.0, steps=1, use_ema=True, name="x", alpha=0.1,
                            kind="a", BOGUS=1)
    with pytest.raises(ValueError, match="BOGUS"):
        Demo.from_argparse(ns)


def test_json_round_trip(tmp_path):
    cfg = Demo(lr=7e-5, inner=Inner(alpha=0.25))
    p = tmp_path / "cfg.json"
    cfg.save_json(str(p))
    assert Demo.parse_file(str(p)) == cfg


def test_extra_keys_forbidden():
    with pytest.raises(Exception):
        Demo(bogus=1)


def test_train_settings_defaults_match_reference():
    # Defaults copied from reference config/train.py:6-41.
    ts = TrainSettings()
    assert ts.batch_size == 2048
    assert ts.microbatch == 64
    assert ts.learning_steps == 320000
    assert ts.ema_rate == "0.5,0.9,0.99"
    assert ts.seed == 102


def test_config_json_overrides_cli(tmp_path):
    # --config_json short-circuits the CLI (reference config/train.py:70-77).
    cfg = TrainSettings(lr=5e-4, seq_len=256)
    p = tmp_path / "train.json"
    cfg.save_json(str(p))
    parser = TrainSettings.to_argparse(add_json=True)
    ns = parser.parse_args(["--config_json", str(p)])
    loaded = TrainSettings.from_argparse(ns)
    assert loaded.lr == 5e-4 and loaded.seq_len == 256


def test_flat_dict_for_model_factory():
    # create_model_from_config(**args.dict()) surface (reference run/train.py:71).
    d = TrainSettings().dict()
    assert "lr" in d and "seq_len" in d and "dp" in d


def test_json_dump_is_loadable_config():
    # README.md:18-21 one-liner: default config dump must parse back.
    blob = TrainSettings().to_json()
    assert TrainSettings.model_validate(json.loads(blob)) == TrainSettings()


def test_config_json_rejects_explicit_default_flag(tmp_path):
    """A flag explicitly set to its default value still conflicts with
    --config_json (true mutual exclusivity, reference config/train.py:63-67).
    The parsed argv is carried on the namespace (as the launcher and
    from_argv record it), never sniffed from the process's sys.argv."""
    from distributed_pipeline_tpu.config.train import TrainSettings

    cfg = tmp_path / "c.json"
    cfg.write_text(TrainSettings().to_json())
    default_lr = TrainSettings().lr
    argv = ["--lr", str(default_lr), "--config_json", str(cfg)]
    parser = TrainSettings.to_argparse(add_json=True)
    ns = parser.parse_args(argv)
    ns._parsed_argv = argv  # what parse_and_autorun/from_argv attach
    with pytest.raises(SystemExit):
        TrainSettings.from_argparse(ns)


def test_config_json_ignores_hosting_process_argv(tmp_path, monkeypatch):
    """A programmatic parse (no recorded argv) must not abort on flags that
    belong to the hosting process's command line."""
    import sys
    from distributed_pipeline_tpu.config.train import TrainSettings

    cfg = tmp_path / "c.json"
    cfg.write_text(TrainSettings().to_json())
    monkeypatch.setattr(sys, "argv", ["driver.py", "--seed", "7"])
    parser = TrainSettings.to_argparse(add_json=True)
    ns = parser.parse_args(["--config_json", str(cfg)])
    settings = TrainSettings.from_argparse(ns)  # must not raise
    assert settings.seed == TrainSettings().seed


def test_abbreviated_flags_rejected():
    """ADVICE r2: allow_abbrev=False — a prefix-abbreviated flag (--log_int)
    must be an argparse error, not silently accepted (it would dodge the
    --config_json mutual-exclusivity scan, which matches exact field names)."""
    from distributed_pipeline_tpu.config.train import TrainSettings

    parser = TrainSettings.to_argparse(add_json=True)
    with pytest.raises(SystemExit):
        parser.parse_args(["--log_int", "50"])
    ns = parser.parse_args(["--log_interval", "50"])  # exact name still works
    assert ns.log_interval == 50
