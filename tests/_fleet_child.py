"""Fixture replica worker for serving-fleet tests (no jax import).

Plays the part of ``run/serve.py``'s ``_fleet_worker_main`` through the
REAL :class:`~distributed_pipeline_tpu.serving.fleet.WorkerProtocol` —
same inbox/outbox/ready/swap/beacon files, same chaos hooks, same
clean-inbox-at-startup contract — so the fleet supervisor, router,
watchdog, hot-swap, and goodput-ledger paths get full end-to-end coverage
in tier-1 without paying a jax import per replica process.

The "model" is a deterministic token function of (prompt, params salt):

    token[k] = (31 * sum(prompt) + 1000 * salt + k) % 50021

so replayed requests are token-identical across replicas at the same
params version (the greedy-decode contract) and a hot-swap visibly
changes outputs. "Checkpoints" are ``model_{step:06d}/params.json``
dirs carrying ``{"step": S, "salt": N}`` next to a commit-marker file;
loading json-parses the payload, so a chaos-garbled swap target fails
validation exactly like a corrupt orbax checkpoint does in the real
worker.

Argv: --fleet_worker_dir DIR --replica_id I --checkpoint_dir CKPTS
      [--step N] [--token_interval_s S] [--startup_s S]
      [--cost_ledger true|false] [--serve_transport file|socket]
      [--prefix_cache true|false]
"""

import argparse
import collections
import json
import os
import time

parser = argparse.ArgumentParser()
parser.add_argument("--fleet_worker_dir", required=True)
parser.add_argument("--replica_id", type=int, required=True)
parser.add_argument("--checkpoint_dir", required=True)
parser.add_argument("--step", type=int, default=1)
parser.add_argument("--token_interval_s", type=float, default=0.003)
parser.add_argument("--startup_s", type=float, default=0.0)
parser.add_argument("--cost_ledger", default="false")
parser.add_argument("--serve_transport", default="file")
parser.add_argument("--prefix_cache", default="false")
parser.add_argument("--page_size", type=int, default=4)
ns = parser.parse_args()

from distributed_pipeline_tpu.chaos import (  # noqa: E402
    CHAOS_PLAN_ENV,
    ChaosInjector,
    ChaosPlan,
)
from distributed_pipeline_tpu.serving.fleet import (  # noqa: E402
    ReplicaPaths,
    WorkerProtocol,
)
from distributed_pipeline_tpu.serving.transport import (  # noqa: E402
    prefix_block_hashes,
)

paths = ReplicaPaths.at(ns.fleet_worker_dir, ns.replica_id)
proto = WorkerProtocol(paths, ns.replica_id,
                       transport=ns.serve_transport)
pin = proto.startup()
if ns.startup_s > 0:
    time.sleep(ns.startup_s)


def load_params(step: int):
    """Raises on a garbled payload — the corrupt-swap validation path."""
    path = os.path.join(ns.checkpoint_dir, f"model_{step:06d}",
                        "params.json")
    with open(path) as f:
        payload = json.load(f)
    return int(payload["step"]), int(payload.get("salt", 0))


plan_src = os.environ.get(CHAOS_PLAN_ENV, "")
injector = (ChaosInjector(ChaosPlan.parse(plan_src), rank=ns.replica_id,
                          run_dir=paths.root) if plan_src else None)

cur_step, salt = load_params(int(pin["step"]) if pin else ns.step)
tick = 0
admitted = 0
completed = 0
tokens_out = 0
in_flight = {}  # id -> [payload, tokens]
t_serve0 = time.time()

# Simulated prefix cache (mirrors the real worker's advertisement):
# leading blocks already served here count as hits; every admitted
# block lands in a bounded LRU that rides the beacon/heartbeat.
prefix_on = ns.prefix_cache.strip().lower() in ("true", "1", "yes")
prefix_index: "collections.OrderedDict" = collections.OrderedDict()
prefix_hits = 0
prefix_misses = 0


def index_prefix(prompt) -> None:
    global prefix_hits, prefix_misses
    if not prefix_on:
        return
    hashes = prefix_block_hashes([int(t) for t in prompt], ns.page_size)
    leading = True
    for h in hashes:
        if leading and h in prefix_index:
            prefix_hits += 1
        else:
            leading = False
            prefix_misses += 1
        prefix_index.pop(h, None)
        prefix_index[h] = True
        while len(prefix_index) > 256:
            prefix_index.popitem(last=False)


def beacon_extra():
    if not prefix_on:
        return None
    return {"prefix_index": list(prefix_index),
            "prefix_hits": prefix_hits, "prefix_misses": prefix_misses}


def write_ledger():
    """Mirror of the real worker's --cost_ledger snapshot: the same
    perf_ledger.json file/row shape in the replica dir (mfu + gap terms
    summing to 1 by construction), so the status/export surfacing is
    provable over a real fleet ring without paying a jax import."""
    if ns.cost_ledger.strip().lower() not in ("true", "1", "yes"):
        return
    from distributed_pipeline_tpu.obs import ledger as ledger_lib
    wall = max(time.time() - t_serve0, 1e-6)
    mfu = 0.01 * (1 + ns.replica_id)
    row = {"program": "serve_decode", "mfu": mfu,
           "tokens_per_s": tokens_out / wall,
           "collective_bytes_per_step": 0.0,
           "padding_waste_frac": 0.25}
    gaps = dict.fromkeys(ledger_lib.GAP_TERMS, 0.0)
    gaps["mfu_gap_residual"] = 1.0 - mfu
    row.update(gaps)
    ledger_lib.write_ledger(ns.fleet_worker_dir, {"serve_decode": row},
                            t=time.time())


def token_fn(prompt, k: int) -> int:
    return (31 * sum(int(t) for t in prompt) + 1000 * salt + k) % 50021


def step_decode() -> bool:
    """One 'decode step': every in-flight request gains one token; the
    shared sleep stands in for device time (continuous batching: the
    step costs one interval regardless of occupancy). Traced like the
    real worker's engine track (DPT_TRACE): one decode_span per step."""
    global completed, tokens_out
    if not in_flight:
        return False
    t0_wall = time.time() if proto.tracer.enabled else 0.0
    time.sleep(ns.token_interval_s)
    now = time.time()
    if proto.tracer.enabled:
        proto.tracer.complete("decode_span", "engine", t0_wall,
                              now - t0_wall,
                              args={"in_flight": len(in_flight)})
    for rk in list(in_flight):
        payload, toks = in_flight[rk]
        toks.append(token_fn(payload["prompt"], len(toks)))
        if len(toks) == 1:
            payload["_ttft"] = now - float(payload.get("submit_t", now))
        if len(toks) >= int(payload["max_new_tokens"]):
            proto.write_result({
                "id": int(payload["id"]), "tokens": toks,
                "ttft_s": payload.get("_ttft"), "params_step": cur_step,
                "replays": int(payload.get("replays", 0))})
            completed += 1
            tokens_out += len(toks)
            del in_flight[rk]
    return True


proto.write_beacon(tick)
proto.announce_ready(cur_step)
write_ledger()

while not proto.stop_requested():
    cmd = proto.pending_swap()
    if cmd is not None:
        with proto.tracker.timed("drain_s"):
            while in_flight:
                step_decode()
                tick += 1
                proto.write_beacon(tick)
        with proto.tracker.timed("swap_s"):
            try:
                cur_step, salt = load_params(int(cmd["step"]))
                ok, err = True, ""
            except Exception as e:  # garbage payload: keep old params
                ok, err = False, f"{type(e).__name__}: {e}"
        if ok:
            proto.announce_ready(cur_step)
        proto.ack_swap(int(cmd["id"]), ok, cur_step, err)
    if injector is not None:
        injector.on_serve_tick(admitted, len(in_flight))
    moved = False
    for payload in proto.poll_inbox():
        in_flight[int(payload["id"])] = [payload, []]
        proto.consume(int(payload["id"]))
        index_prefix(payload["prompt"])
        admitted += 1
        moved = True
    moved = step_decode() or moved
    tick += 1
    proto.write_beacon(tick, extra=beacon_extra())
    if not moved:
        time.sleep(0.003)

with proto.tracker.timed("drain_s"):
    while in_flight:
        step_decode()
        tick += 1
        proto.write_beacon(tick)
write_ledger()
proto.write_sidecar({"ticks": tick, "admitted": admitted,
                     "completed": completed, "tokens": tokens_out,
                     "params_step": cur_step,
                     "prefix_hits": prefix_hits,
                     "prefix_misses": prefix_misses})
proto.tracer.close()
proto.close()
raise SystemExit(0)
