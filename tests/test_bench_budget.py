"""Time-to-signal contracts: the streaming budget-aware bench, the
persistent-compilation-cache wiring, and the trainer's AOT compile metrics.

The r5 postmortem (VERDICT.md weak #1-2): bench.py printed its single JSON
line only at the very end, so a driver timeout captured ZERO of the twelve
legs' work. These tests pin the replacement contract — headline-first leg
order, incremental JSONL persistence, budget-skip markers that still yield a
parseable final line — and the compile-cache path that makes warm runs
near-compile-free.
"""

import json
import os
import subprocess
import sys

import jax
import pytest

from distributed_pipeline_tpu.config.train import TrainSettings
from distributed_pipeline_tpu.parallel import make_mesh
from distributed_pipeline_tpu.parallel.launcher import _worker_env
from distributed_pipeline_tpu.utils.perf import (
    AOTStep,
    enable_persistent_compilation_cache,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ bench harness

@pytest.fixture(scope="module")
def bench_run(tmp_path_factory):
    """One constrained-budget bench subprocess, shared by the contract
    tests: BENCH_BUDGET_S=1 forces every leg after the headline to be
    budget-skipped (the headline is exempt by contract)."""
    tmp = tmp_path_factory.mktemp("bench")
    # Pre-seed the HISTORY with a prior run's row: the append contract
    # (ISSUE 14) says bench extends the time series, never truncates it.
    history = tmp / "history.jsonl"
    history.write_text(json.dumps(
        {"name": "diffuseq-base-seq128", "tokens_per_sec_per_chip": 1.0,
         "run_id": "prior-run", "t": 0.0}) + "\n")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "1",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_HISTORY": str(history),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        # glob: the headline + its satellite twins — enough legs to
        # observe ordering and skipping without a multi-minute test
        # (BENCH_ONLY without a wildcard is an EXACT match now)
        "BENCH_ONLY": "diffuseq-base-seq128*",
    })
    # The conftest's 8-fake-device XLA_FLAGS would leak into the subprocess
    # and change the bench's dp=-1 mesh; the bench contract is about the
    # default single-device CPU environment.
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    return proc, tmp / "legs.jsonl", history


def test_bench_budget_exits_zero_with_parseable_json(bench_run):
    proc, _, _ = bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    assert final["configs"], final
    assert final["budget_s"] == 1.0


def test_bench_headline_leg_completes_first(bench_run):
    proc, _, _ = bench_run
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    head = final["configs"][0]
    # The headline leg is exempt from the budget guard: it carries real
    # numbers (and the compile/steady split) even when the budget is blown
    # before it finishes.
    assert head["name"] == "diffuseq-base-seq128"
    assert "skipped" not in head and "error" not in head
    assert head["tokens_per_sec_per_chip"] > 0
    assert head["compile_s"] > 0
    assert head["first_step_s"] >= head["compile_s"]
    assert final["value"] == head["tokens_per_sec_per_chip"]


def test_bench_budget_exhaustion_yields_skip_markers(bench_run):
    proc, _, _ = bench_run
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    skipped = [c for c in final["configs"] if c.get("skipped") == "budget"]
    assert skipped, "1s budget must skip every non-headline leg"
    assert all(set(c) == {"name", "skipped"} for c in skipped)
    # every leg is accounted for: completed or explicitly skipped
    # (headline + prefetch A/B twin + zero1 A/B + trace A/B + chaos +
    # elastic + tune + mpmd-pipe + noaccum + moe8 + moe8-cf1 + scan +
    # fusedupd)
    assert len(final["configs"]) == 13


def test_bench_artifact_is_valid_jsonl_of_all_legs(bench_run):
    proc, artifact, _ = bench_run
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    rows = [json.loads(line) for line in
            artifact.read_text().strip().splitlines()]
    # the incrementally-persisted artifact IS the final configs list — a
    # timeout after leg k would still have left rows 0..k on disk
    assert rows == final["configs"]


def test_bench_headline_row_carries_the_cost_ledger(bench_run):
    """ISSUE 14 acceptance: the headline train row carries a POPULATED
    ledger — collective_bytes_per_step present, mfu_gap_* summing with
    the (unrounded) mfu to exactly 1 (residual-by-construction, 1e-6),
    padding waste inside [0, 1]."""
    from distributed_pipeline_tpu.obs import ledger as ledger_lib

    proc, _, _ = bench_run
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    head = final["configs"][0]
    assert "collective_bytes_per_step" in head
    for term in ledger_lib.GAP_TERMS:
        assert term in head and head[term] >= 0
    assert abs(ledger_lib.gap_sum_identity(head) - 1.0) < 1e-6
    assert 0 <= head["padding_waste_frac"] <= 1
    assert head["flops_per_execution"] > 0
    assert head["bytes_accessed"] > 0


def test_bench_history_appends_without_truncating(bench_run):
    """The bench_history.jsonl contract (ISSUE 14): bench APPENDS every
    leg row stamped with one run_id per invocation — the pre-seeded
    prior run's row survives, the new rows share a fresh id, and the
    sentinel's grouping sees two runs in file order."""
    from distributed_pipeline_tpu.chaos.goodput import read_journal
    from distributed_pipeline_tpu.obs import regress as regress_lib

    proc, artifact, history = bench_run
    rows = read_journal(str(history))
    assert rows[0]["run_id"] == "prior-run", "history was truncated"
    new = [r for r in rows if r.get("run_id") != "prior-run"]
    artifact_rows = [json.loads(l) for l in
                     artifact.read_text().strip().splitlines()]
    assert len(new) == len(artifact_rows)
    assert len({r["run_id"] for r in new}) == 1  # one id per invocation
    assert all("t" in r for r in new)
    runs = regress_lib.group_runs(rows)
    assert len(runs) == 2 and runs[0][0] == "prior-run"


@pytest.mark.lint
def test_regress_sentinel_exits_nonzero_on_injected_regression(tmp_path):
    """CI wiring (ISSUE 14): ``python -m distributed_pipeline_tpu.obs.
    regress`` must exit nonzero when the newest recorded run regresses
    past the band, and zero on a flat history — the property a CI job
    gates on."""
    def rows(tps3):
        return [json.dumps({"name": "diffuseq-base-seq128",
                            "tokens_per_sec_per_chip": tps,
                            "mfu": 0.5, "peak_live_bytes": 100,
                            "recompile_count": 0, "run_id": f"r{i}",
                            "t": 1.0})
                for i, tps in enumerate([1000, 1005, tps3], 1)]

    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    flat, reg = tmp_path / "flat.jsonl", tmp_path / "reg.jsonl"
    flat.write_text("\n".join(rows(1002)) + "\n")
    reg.write_text("\n".join(rows(900)) + "\n")
    base = [sys.executable, "-m", "distributed_pipeline_tpu.obs.regress",
            "--history"]
    ok = subprocess.run(base + [str(flat)], capture_output=True,
                        text=True, env=env, cwd=REPO)
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout)["verdict"] == "ok"
    bad = subprocess.run(base + [str(reg)], capture_output=True,
                         text=True, env=env, cwd=REPO)
    assert bad.returncode == 1, (bad.returncode, bad.stderr)
    assert json.loads(bad.stdout)["verdict"] == "regressed"
    assert "regressed" in bad.stderr  # the human table names the leg


def test_bench_only_exact_match_with_optional_glob():
    """BENCH_ONLY leg selection (ISSUE 9 satellite): a bare name is an
    EXACT match — the old substring filter made
    BENCH_ONLY=diffuseq-base-seq128 run SEVEN legs, the chaos leg
    included — and a wildcard pattern is an fnmatch glob."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "bench_for_test", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    legs = [(n, None) for n in (
        "diffuseq-base-seq128", "diffuseq-base-seq128-prefetch",
        "diffuseq-base-seq128-zero1", "diffuseq-base-seq128-chaos",
        "diffuseq-base-seq128-tune",
        "gpt2-serve-decode-b64", "gpt2-serve-spec-decode",
        "gpt2-serve-decode-int8", "gpt2-base-decode-oneshot-b1",
        "gpt2-serve-fleet-chaos", "gpt2-serve-autoscale")]
    names = lambda got: [n for n, _ in got]
    assert names(bench.select_legs(legs, "diffuseq-base-seq128")) == \
        ["diffuseq-base-seq128"]
    assert names(bench.select_legs(legs, "diffuseq-base-seq128*")) == \
        ["diffuseq-base-seq128", "diffuseq-base-seq128-prefetch",
         "diffuseq-base-seq128-zero1", "diffuseq-base-seq128-chaos",
         "diffuseq-base-seq128-tune"]
    assert names(bench.select_legs(legs, "*serve-decode*")) == \
        ["gpt2-serve-decode-b64", "gpt2-serve-decode-int8"]
    # the fleet leg must NOT ride the headline glob (it sits after it so
    # a timeout degrades to an error row, never a blocked headline)
    assert names(bench.select_legs(legs, "gpt2-serve-fleet-chaos")) == \
        ["gpt2-serve-fleet-chaos"]
    # same contract for the autoscale leg (ISSUE 17): gpt2-named, so the
    # diffuseq headline glob can never pick it up
    assert names(bench.select_legs(legs, "gpt2-serve-autoscale")) == \
        ["gpt2-serve-autoscale"]
    assert bench.select_legs(legs, "") == legs
    assert bench.select_legs(legs, "no-such-leg") == []


# ----------------------------------------------------- serving decode legs

@pytest.fixture(scope="module")
def serve_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the three serving decode legs
    (ISSUE 7): parsed rows must land in the JSONL artifact with the
    serving schema columns."""
    tmp = tmp_path_factory.mktemp("serve_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "240",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "*serve-decode*",
        "BENCH_HISTORY": "",
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    return proc, tmp / "legs.jsonl"


def test_serve_bench_legs_land_parsed_rows(serve_bench_run):
    """The three continuous-batching legs (slots 1 / 8 / 64) complete and
    carry the serving schema: decode_tokens_per_s_per_chip and
    time_to_first_token_s, plus the steady recompile_count gauge at 0
    (prefill/decode compiled exactly once, in warmup)."""
    proc, artifact = serve_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    from distributed_pipeline_tpu.obs import ledger as ledger_lib

    for slots in (1, 8, 64):
        row = rows[f"gpt2-serve-decode-b{slots}"]
        assert "error" not in row and "skipped" not in row, row
        assert row["batch"] == slots
        assert row["decode_tokens_per_s_per_chip"] > 0
        assert row["time_to_first_token_s"] > 0
        assert row["ttft_p95_s"] >= 0
        assert row["compile_s"] > 0
        assert row["recompile_count"] == 0, (
            "steady-state serving recompiled", row)
        # ISSUE 14 acceptance (b8 named explicitly): serve rows carry a
        # populated decode ledger with the exact gap-sum identity and
        # steady recompiles still 0
        assert "collective_bytes_per_step" in row
        assert abs(ledger_lib.gap_sum_identity(row) - 1.0) < 1e-6
        assert 0 <= row["padding_waste_frac"] <= 1
        assert 0 <= row["prefill_padding_waste_frac"] <= 1


def test_serve_bench_final_json_carries_rows(serve_bench_run):
    proc, artifact = serve_bench_run
    final = json.loads(proc.stdout.strip().splitlines()[-1])
    rows = [json.loads(line) for line in
            artifact.read_text().strip().splitlines()]
    assert rows == final["configs"]
    # continuous batching scales decode throughput with occupancy: 64
    # full slots must beat one slot by a wide margin even on CPU (the
    # >= 3x acceptance ratio vs one-shot b1 is asserted on the real
    # artifact's serve-vs-oneshot-decode row, emitted in full runs)
    by = {r["name"]: r for r in rows}
    assert (by["gpt2-serve-decode-b64"]["decode_tokens_per_s_per_chip"]
            > 3 * by["gpt2-serve-decode-b1"]
            ["decode_tokens_per_s_per_chip"])


# ------------------------------------------------- serving fleet leg

@pytest.fixture(scope="module")
def fleet_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the serving-fleet resilience leg
    (ISSUE 11): 3 replica worker processes, Poisson load, one injected
    kill_replica mid-request, one checkpoint hot-swap. chaos-marked: it
    spawns a real multi-process fleet."""
    tmp = tmp_path_factory.mktemp("fleet_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "240",
        "BENCH_LEG_BUDGET_S": "240",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "gpt2-serve-fleet-chaos",
        "BENCH_HISTORY": "",
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    return proc, tmp / "legs.jsonl"


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_bench_leg_meets_serving_slos(fleet_bench_run):
    """The acceptance row: zero dropped admitted requests, >= 1 replay
    (the injected kill), hot-swap ok, TTFT p50/p95 inside the documented
    SLO bounds, and the serving ledger accounting every replica-second."""
    proc, artifact = fleet_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    row = rows["gpt2-serve-fleet-chaos"]
    assert "error" not in row and "skipped" not in row, row
    assert row["dropped"] == 0
    assert row["replayed"] >= 1
    assert row["swap_ok"] is True and row["swap_step"] == 4
    assert row["ttft_p50_s"] <= row["slo_p50_s"]
    assert row["ttft_p95_s"] <= row["slo_p95_s"]
    assert row["accounted_frac"] == pytest.approx(1.0, abs=0.05)
    assert row["completed"] == row["requests"]
    assert row["replay_s"] >= 0 and row["fleet_attempts"] >= 4


# -------------------------------------------------- autoscale fleet leg

@pytest.fixture(scope="module")
def autoscale_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the autoscaling-fleet leg
    (ISSUE 17): three fleet runs over one checkpoint — affinity A/B,
    static-max baseline, and --replicas 1 under the SLO autoscaler on
    seeded diurnal traffic. BENCH_HISTORY is SET (unlike the other leg
    fixtures): the acceptance also covers the row landing in the
    history file under the regression sentinel's grouping."""
    tmp = tmp_path_factory.mktemp("autoscale_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "600",
        "BENCH_LEG_BUDGET_S": "600",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "gpt2-serve-autoscale",
        "BENCH_HISTORY": str(tmp / "history.jsonl"),
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=700)
    return proc, tmp / "legs.jsonl", tmp / "history.jsonl"


@pytest.mark.slow
@pytest.mark.chaos
def test_autoscale_bench_leg_meets_acceptance(autoscale_bench_run):
    """ISSUE 17 acceptance row: >= 1 journaled scale-up AND drain-based
    scale-down, zero drops, p95 TTFT under the documented CPU SLO, the
    autoscaled replica-seconds bill strictly below the static-max
    baseline, affinity's fleet-wide prefix hit rate strictly above
    least-loaded's, and the ledger closing at accounted_frac 1.0 with
    paid_idle booked."""
    proc, artifact, _ = autoscale_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    row = rows["gpt2-serve-autoscale"]
    assert "error" not in row and "skipped" not in row, row
    assert row["dropped"] == 0
    assert row["completed"] == row["requests"]
    assert row["scale_ups"] >= 1
    assert row["scale_downs"] >= 1
    assert row["ttft_p50_s"] <= row["slo_p50_s"]
    assert row["ttft_p95_s"] <= row["slo_p95_s"]
    assert row["replica_seconds"] < row["static_replica_seconds"]
    assert row["replica_seconds_saved_frac"] > 0
    assert row["prefix_hit_rate_affinity"] > \
        row["prefix_hit_rate_least_loaded"]
    assert row["paid_idle_s"] is not None and row["paid_idle_s"] >= 0
    assert row["accounted_frac"] == pytest.approx(1.0, abs=0.05)


@pytest.mark.slow
@pytest.mark.chaos
def test_autoscale_bench_row_lands_in_history(autoscale_bench_run):
    """ISSUE 17 satellite: the new leg's row rides bench_history.jsonl
    under the obs/regress.py sentinel — stamped with the invocation's
    run_id and grouped as one run by the sentinel's own reader."""
    from distributed_pipeline_tpu.chaos.goodput import read_journal
    from distributed_pipeline_tpu.obs import regress as regress_lib

    proc, _, history = autoscale_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = read_journal(str(history))
    mine = [r for r in rows if r["name"] == "gpt2-serve-autoscale"]
    assert len(mine) == 1 and "error" not in mine[0]
    assert mine[0].get("run_id") and "t" in mine[0]
    runs = regress_lib.group_runs(rows)
    assert len(runs) == 1 and runs[0][0] == mine[0]["run_id"]


# ------------------------------------------------------ auto-tuner leg

@pytest.fixture(scope="module")
def tune_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the auto-tuner leg (ISSUE 13):
    a screen-only budgeted layout search on the forced-host dp=2 CPU
    mesh. slow-marked consumer: the leg spawns ~9 measurement children."""
    tmp = tmp_path_factory.mktemp("tune_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "240",
        "BENCH_LEG_BUDGET_S": "240",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "diffuseq-base-seq128-tune",
        "BENCH_HISTORY": "",
    })
    env.pop("XLA_FLAGS", None)
    env.pop("DPT_TUNE_INJECT", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    return proc, tmp / "legs.jsonl"


@pytest.mark.slow
def test_tune_bench_leg_reproduces_or_beats_hand_tuned(tune_bench_run):
    """The acceptance row: the tuner's winner reproduces or beats the
    hand-tuned family table within the +-3% band, every enumerated
    candidate is accounted (measured + pruned + rejected + skipped ==
    enumerated), and the winner holds steady recompiles at 0."""
    proc, artifact = tune_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    row = rows["diffuseq-base-seq128-tune"]
    assert "error" not in row and "skipped" not in row, row
    assert row["winner_vs_baseline"] >= 1.0 - row["noise_band_pct"] / 100
    assert (row["measured"] + row["pruned"] + row["rejected"]
            + row["skipped"]) == row["enumerated"]
    assert row["enumerated"] > row["measured"] > 0
    assert row["steady_recompile_count"] == 0
    assert row["winner"].startswith("diffuseq-m")


# ------------------------------------------------- trace-overhead A/B leg

@pytest.fixture(scope="module")
def trace_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the trace-overhead A/B leg
    (ISSUE 12): span tracing ON vs OFF, paired-interleaved at headline
    settings."""
    tmp = tmp_path_factory.mktemp("trace_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "240",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "diffuseq-base-seq128-trace",
        "BENCH_HISTORY": "",
    })
    env.pop("XLA_FLAGS", None)
    env.pop("DPT_TRACE", None)  # the leg arms its ON arm itself
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=420)
    return proc, tmp / "legs.jsonl"


def test_trace_ab_leg_emits_paired_delta_row(trace_bench_run):
    """The trace-overhead guard's schema: the leg carries the paired
    ab_* fields and a non-empty ON-arm shard (a disarmed tracer would
    'prove' a zero cost nobody pays), and the derived trace-ab-delta
    row restates the same paired numbers. The +-3% noise-band claim is
    about the captured full-run artifact, not asserted here — a loaded
    CI box would flake it; what IS pinned is that both arms ran
    interleaved with even (position-balanced) rounds."""
    proc, artifact = trace_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    leg = rows["diffuseq-base-seq128-trace"]
    assert "error" not in leg and "skipped" not in leg, leg
    assert leg["ab_method"] == "paired-interleaved"
    assert leg["ab_rounds"] % 2 == 0
    assert leg["trace_events"] > 0
    assert leg["steps_per_s"] > 0 and leg["ab_off_steps_per_s"] > 0
    delta = rows["trace-ab-delta"]
    assert delta["delta_pct"] == leg["ab_delta_pct"]
    assert delta["on_steps_per_s"] == leg["steps_per_s"]
    assert delta["off_steps_per_s"] == leg["ab_off_steps_per_s"]
    assert delta["trace_events"] == leg["trace_events"]


# ------------------------------------------------ compilation-cache wiring

def test_compilation_cache_flag_roundtrips_through_settings():
    s = TrainSettings.from_argv(["--compilation_cache_dir", "/tmp/cc"])
    assert s.compilation_cache_dir == "/tmp/cc"
    assert TrainSettings().compilation_cache_dir == "auto"
    # and through the JSON path (the --config_json workflow)
    s2 = TrainSettings.model_validate(json.loads(s.to_json()))
    assert s2.compilation_cache_dir == "/tmp/cc"


def test_enable_persistent_cache_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    assert enable_persistent_compilation_cache("off") == ""
    assert enable_persistent_compilation_cache("auto", run_dir="") == ""
    try:
        d = enable_persistent_compilation_cache("auto",
                                                run_dir=str(tmp_path))
        assert d == os.path.join(str(tmp_path), "compile_cache")
        assert os.path.isdir(d)
        # exported so spawned workers inherit the same cache
        assert os.environ["JAX_COMPILATION_CACHE_DIR"] == d
    finally:
        # "off" resets jax's once-only cache object too — leaving it
        # initialized would pin this tmp dir for the whole test process
        enable_persistent_compilation_cache("off")


def test_cache_dir_reaches_worker_env(tmp_path):
    env = _worker_env(1, 2, "127.0.0.1:9999", 2, run_timestamp="20260803",
                      cache_dir=str(tmp_path))
    assert env["JAX_COMPILATION_CACHE_DIR"] == str(tmp_path)
    assert env["JAX_PROCESS_INDEX"] == "1"
    assert env["DPT_RUN_TIMESTAMP"] == "20260803"


def test_launcher_forwards_cache_env_to_ring(monkeypatch, tmp_path):
    from distributed_pipeline_tpu.parallel import launcher

    from tests._fake_ring import make_fake_ring

    fake = make_fake_ring()
    monkeypatch.setattr(launcher, "_run_worker_ring", fake)
    monkeypatch.setenv("JAX_COMPILATION_CACHE_DIR", str(tmp_path))
    assert launcher.run_argv_as_distributed("mod", [], nprocs=2) == 0
    assert fake.calls[0]["cache_dir"] == str(tmp_path)


# ------------------------------------------------- AOT compile-time metrics

def _tiny_loop(tmp_path, tag):
    from distributed_pipeline_tpu.data import load_data_from_args
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    wl = create_model_from_config(
        model_family="gpt2", vocab_size=64, seq_len=16, hidden_size=32,
        num_layers=2, num_heads=2, dtype="float32")
    data = load_data_from_args("train", batch_size=8, dataset="synthetic-lm",
                               seq_len=16, vocab_size=64, seed=0)
    return TrainLoop(model=wl, data=data, batch_size=8, lr=1e-3,
                     learning_steps=100, log_interval=10 ** 9,
                     save_interval=10 ** 9, mesh=make_mesh(dp=8),
                     checkpoint_dir=str(tmp_path / tag), seed=5)


def test_aot_compile_metrics_and_cache_hit_path(tmp_path, monkeypatch):
    """compile_time_s/time_to_first_step_s are populated by the first step,
    and a RESUMED TrainLoop under a warm persistent cache compiles
    measurably faster — the exact elastic-restart path the cache exists
    for. The resume leg doubles as a regression test for donating
    orbax-restored buffers into a cache-deserialized executable (jaxlib
    0.4.37 CPU heap corruption; trainer copies restored trees)."""
    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    try:
        enable_persistent_compilation_cache(str(tmp_path / "cache"))

        cold = _tiny_loop(tmp_path, "run")
        assert cold.compile_time_s is None  # nothing compiled at build time
        cold.run_step(next(cold.data))
        assert cold.compile_time_s > 0
        assert cold.time_to_first_step_s >= cold.compile_time_s
        assert os.listdir(str(tmp_path / "cache")), \
            "persistent cache wrote nothing"
        cold.save()

        warm = _tiny_loop(tmp_path, "run")  # same dir: auto-resumes
        assert warm.step == 1
        warm.run_step(next(warm.data))
        warm.run_step(next(warm.data))  # steady state past the restore
        # The XLA compile is the dominant share of the cold number; a cache
        # hit replaces it with a disk read. 0.7 leaves headroom for the
        # (uncached) trace+lower share while still failing if the cache
        # silently stopped hitting.
        assert warm.compile_time_s < cold.compile_time_s * 0.7, (
            warm.compile_time_s, cold.compile_time_s)
    finally:
        enable_persistent_compilation_cache("off")


def test_aot_step_recompiles_on_shape_change():
    calls = []
    step = AOTStep(jax.jit(lambda x: x * 2), "mul",
                   on_compile=lambda n, s: calls.append((n, s)))
    import jax.numpy as jnp
    a = step(jnp.ones((4,)))
    b = step(jnp.ones((4,)))          # same shape: no recompile
    assert len(calls) == 1
    c = step(jnp.ones((8,)))          # shape change: falls back to recompile
    assert len(calls) == 2
    assert float(a.sum()) == 8 and float(b.sum()) == 8
    assert float(c.sum()) == 16
    assert step.compile_time_s == pytest.approx(sum(s for _, s in calls))


def test_get_batch_length_hook_feeds_samples(tmp_path):
    """The reference's get_batch_length user hook: overriding it changes the
    cumulative ``samples`` gauge without touching the loop."""
    import numpy as np

    from distributed_pipeline_tpu.utils import logger
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    class HalfCounted(TrainLoop):
        def get_batch_length(self, batch):
            return super().get_batch_length(batch) // 2

    from distributed_pipeline_tpu.data import load_data_from_args
    from distributed_pipeline_tpu.models import create_model_from_config
    wl = create_model_from_config(
        model_family="gpt2", vocab_size=64, seq_len=16, hidden_size=32,
        num_layers=2, num_heads=2, dtype="float32")
    data = load_data_from_args("train", batch_size=8, dataset="synthetic-lm",
                               seq_len=16, vocab_size=64, seed=0)
    loop = HalfCounted(model=wl, data=data, batch_size=8, lr=1e-3,
                       learning_steps=100, log_interval=10 ** 9,
                       save_interval=10 ** 9, mesh=make_mesh(dp=8),
                       checkpoint_dir=str(tmp_path), seed=5)
    with logger.scoped_configure(format_strs=[]):
        loop.run_step(next(loop.data))
        loop.run_step(next(loop.data))
        kvs = logger.getkvs()
    assert kvs["samples"] == 2 * (8 // 2)  # hook value, not step*batch
    assert loop.get_batch_length(next(loop.data)) == 4


# ------------------------------------------- pallas fast-path legs (ISSUE 18)

@pytest.fixture(scope="module")
def decode_kernel_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the flash-decode kernel leg: the
    same serve loop twice (decode_impl pallas vs xla) over one checkpoint,
    with the kernel arm's schedule-derived HBM bytes landed next to the
    XLA twin's cost-analysis bytes. BENCH_HISTORY is SET — the acceptance
    covers the row riding the history file."""
    tmp = tmp_path_factory.mktemp("decode_kernel_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "600",
        "BENCH_LEG_BUDGET_S": "600",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "gpt2-serve-decode-kernel",
        "BENCH_HISTORY": str(tmp / "history.jsonl"),
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=700)
    return proc, tmp / "legs.jsonl", tmp / "history.jsonl"


@pytest.mark.slow
@pytest.mark.chaos
def test_decode_kernel_bench_leg_meets_acceptance(decode_kernel_bench_run):
    """ISSUE 18 acceptance row: greedy tokens identical to the XLA paged
    path, zero steady-window recompiles on BOTH arms, and the kernel's
    per-token HBM bytes strictly below the gather path's."""
    proc, artifact, history = decode_kernel_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    row = rows["gpt2-serve-decode-kernel"]
    assert "error" not in row and "skipped" not in row, row
    assert row["tokens_identical_to_xla"] is True
    assert row["recompile_count"] == 0
    assert row["xla_recompile_count"] == 0
    assert row["decode_hbm_bytes_per_token"] < \
        row["xla_decode_bytes_per_token"]
    assert 0 < row["hbm_bytes_ratio"] < 1
    assert row["decode_tokens_per_s_per_chip"] > 0
    hist = [json.loads(line) for line in
            history.read_text().strip().splitlines()]
    mine = [r for r in hist if r["name"] == "gpt2-serve-decode-kernel"]
    assert len(mine) == 1 and mine[0].get("run_id")


@pytest.fixture(scope="module")
def fusedupd_bench_run(tmp_path_factory):
    """One bench subprocess filtered to the fused-update twin of the
    headline train leg: same model/step with --fused_update, the kernel's
    read/write-census bytes landed next to the staged optax chain's
    cost-analysis bytes."""
    tmp = tmp_path_factory.mktemp("fusedupd_bench")
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "BENCH_BUDGET_S": "600",
        "BENCH_LEG_BUDGET_S": "600",
        "BENCH_ARTIFACT": str(tmp / "legs.jsonl"),
        "BENCH_CACHE_DIR": str(tmp / "cache"),
        "BENCH_ONLY": "diffuseq-base-seq128-fusedupd",
        "BENCH_HISTORY": str(tmp / "history.jsonl"),
    })
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=700)
    return proc, tmp / "legs.jsonl", tmp / "history.jsonl"


@pytest.mark.slow
@pytest.mark.chaos
def test_fusedupd_bench_leg_meets_acceptance(fusedupd_bench_run):
    """ISSUE 18 acceptance row: the fused-update leg completes with real
    throughput, its one-pass update bytes strictly below the staged
    chain's, and the row rides the history file."""
    proc, artifact, history = fusedupd_bench_run
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = {r["name"]: r for r in
            (json.loads(line) for line in
             artifact.read_text().strip().splitlines())}
    row = rows["diffuseq-base-seq128-fusedupd"]
    assert "error" not in row and "skipped" not in row, row
    assert row["fused_update"] is True
    assert row["tokens_per_sec_per_chip"] > 0
    assert row["update_hbm_bytes_per_step"] < \
        row["xla_update_bytes_per_step"]
    assert 0 < row["update_bytes_ratio"] < 1
    hist = [json.loads(line) for line in
            history.read_text().strip().splitlines()]
    mine = [r for r in hist if r["name"] == "diffuseq-base-seq128-fusedupd"]
    assert len(mine) == 1 and mine[0].get("run_id")
