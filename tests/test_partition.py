"""Auto-sharding engine tests (ISSUE 9): the regex partition rules, their
leaf-for-leaf equivalence with the retired hand-wired path, ZeRO-1
cross-replica optimizer/EMA sharding (bit-identical losses, ~dp x
per-replica memory drop), and checkpoint round-trip / walk-back of the
sharded state.
"""

import jax
import numpy as np
import pytest
from flax import linen as nn
from jax.sharding import PartitionSpec as P

from distributed_pipeline_tpu.data import load_data_from_args
from distributed_pipeline_tpu.models import create_model_from_config
from distributed_pipeline_tpu.parallel import make_mesh
from distributed_pipeline_tpu.parallel import partition as pt
from distributed_pipeline_tpu.parallel.sharding import param_shardings
from distributed_pipeline_tpu.utils import checkpoint as ckpt
from distributed_pipeline_tpu.utils.trainer import TrainLoop


def tiny_workload(fam="gpt2", **kw):
    return create_model_from_config(
        model_family=fam, vocab_size=64, seq_len=16, hidden_size=32,
        num_layers=2, num_heads=2, diffusion_steps=50, dtype="float32",
        **kw)


def tiny_data(fam="gpt2", batch_size=8, seed=0):
    name = "synthetic-lm" if fam == "gpt2" else "synthetic-seq2seq"
    return load_data_from_args("train", batch_size=batch_size, dataset=name,
                               seq_len=16, vocab_size=64, seed=seed)


def make_loop(tmp_path, fam="gpt2", **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("lr", 1e-3)
    kw.setdefault("learning_steps", 1000)
    kw.setdefault("log_interval", 10 ** 9)
    kw.setdefault("save_interval", 10 ** 9)
    kw.setdefault("mesh", make_mesh(dp=8))
    kw.setdefault("ema_rate", "0.9")
    kw.setdefault("seed", 5)
    data = kw.pop("data", None) or tiny_data(fam, kw["batch_size"])
    return TrainLoop(model=tiny_workload(fam), data=data,
                     checkpoint_dir=str(tmp_path), **kw)


# ----------------------------------------------------------- rule matching


def test_match_rules_first_match_wins_and_scalars_skip():
    tree = {"params": {"attn": {"qkv": np.zeros((8, 4)),
                                "scale": np.zeros(())},
                       "one": np.zeros((1,))}}
    rules = ((r"attn/qkv$", P("data", None)),
             (r"attn/", P("fsdp")),   # shadowed for qkv by the rule above
             (r".*", P()))
    specs = pt.match_partition_rules(rules, tree)
    assert specs["params"]["attn"]["qkv"] == P("data", None)
    # scalar and single-element leaves never partition, whatever matches
    assert specs["params"]["attn"]["scale"] == P()
    assert specs["params"]["one"] == P()


def test_match_rules_requires_explicit_catchall():
    tree = {"a": {"w": np.zeros((4, 4))}}
    with pytest.raises(ValueError, match="catch-all"):
        pt.match_partition_rules(((r"nomatch", P("data")),), tree)


def test_match_rules_rejects_overlong_spec():
    tree = {"w": np.zeros((4,))}
    with pytest.raises(ValueError, match="rank"):
        pt.match_partition_rules(((r".*", P("data", None)),), tree)


def test_fix_spec_drops_nondividing_axes():
    mesh = make_mesh(dp=8)
    # dim 0 (3) does not divide dp=8 -> replicated; scalar axis sizes drop
    assert pt.fix_spec(mesh, P("data", "tensor"), (3, 8)) == P(None, None)
    assert pt.fix_spec(mesh, P("data"), (16, 4)) == P("data", None)


def test_parse_partition_rules_inline_and_file(tmp_path):
    raw = '[["attn/qkv$", ["fsdp", null, ["tensor", "data"]]], [".*", []]]'
    rules = pt.parse_partition_rules(raw)
    assert rules[0] == ("attn/qkv$", P("fsdp", None, ("tensor", "data")))
    assert rules[-1] == (".*", P())
    f = tmp_path / "rules.json"
    f.write_text(raw)
    assert pt.parse_partition_rules("@" + str(f)) == rules
    assert pt.parse_partition_rules(str(f)) == rules
    assert pt.parse_partition_rules("") is None
    with pytest.raises(ValueError, match="pairs"):
        pt.parse_partition_rules('[["only-a-regex"]]')


# ------------------------------------- equivalence with the hand-wired path


MODELS = {
    "diffuseq": dict(model_family="diffuseq"),
    "gpt2": dict(model_family="gpt2"),
    "diffuseq-moe": dict(model_family="diffuseq", moe_experts=4,
                         moe_top_k=2),
    "gpt2-scan": dict(model_family="gpt2", scan_layers=True),
    "gpt2-scan-moe": dict(model_family="gpt2", scan_layers=True,
                          moe_experts=4, moe_every=2),
}
MESHES = {
    "dp8": dict(dp=8),
    "dp2-fsdp2-tensor2": dict(dp=2, fsdp=2, tensor=2),
    "fsdp8": dict(dp=1, fsdp=8),
    "dp2-expert4": dict(dp=2, expert=4),
    "dp2-fsdp2-pipe2": dict(dp=2, fsdp=2, pipe=2),
}


@pytest.mark.parametrize("model_name", sorted(MODELS))
@pytest.mark.parametrize("mesh_name", sorted(MESHES))
def test_rule_tables_reproduce_handwired_shardings(model_name, mesh_name):
    """The per-model rule tables must reproduce the flax-logical-metadata
    shardings leaf for leaf on every mesh shape — the guarantee that
    swapping engines changed NOTHING about the headline layout (and
    therefore nothing about its numerics: same shardings, same program)."""
    wl = create_model_from_config(
        hidden_size=64, num_layers=2, num_heads=4, vocab_size=256,
        seq_len=32, dtype="float32", **MODELS[model_name])
    mesh = make_mesh(**MESHES[mesh_name])
    abstract = jax.eval_shape(wl.init_params, jax.random.PRNGKey(0))
    unboxed = nn.meta.unbox(abstract)
    rules = pt.rules_for_workload(wl)
    assert rules is not None and rules[-1][0] == r".*"
    engine = pt.resolve_shardings(
        mesh, pt.match_partition_rules(rules, unboxed), unboxed)
    legacy = param_shardings(mesh, abstract)
    legacy_leaves, _ = jax.tree_util.tree_flatten_with_path(legacy)
    engine_leaves = jax.tree_util.tree_leaves(engine)
    shape_leaves = jax.tree_util.tree_leaves(unboxed)
    assert len(legacy_leaves) == len(engine_leaves) > 0
    for (path, lg), en, leaf in zip(legacy_leaves, engine_leaves,
                                    shape_leaves):
        assert lg.is_equivalent_to(en, len(leaf.shape)), (
            f"{pt.tree_path_name(path)}: legacy {lg.spec} != engine "
            f"{en.spec} for shape {leaf.shape}")


def test_rules_for_workload_fallback():
    wl = tiny_workload("gpt2")
    assert pt.rules_for_workload(wl) == wl.partition_rules is not None

    class Custom:
        family = "somethingelse"

    assert pt.rules_for_workload(Custom()) is None


def test_rule_engine_vs_legacy_training_bit_identical(tmp_path,
                                                      monkeypatch):
    """Same shardings => same compiled program => bit-identical training.
    The legacy path is forced by stripping the workload's declared table
    (what any unknown model family gets)."""
    batches = [next(tiny_data("gpt2", 8, seed=3)) for _ in range(4)]
    losses = {}
    for mode in ("rules", "legacy"):
        if mode == "legacy":
            monkeypatch.setattr(pt, "rules_for_workload", lambda wl: None)
        loop = make_loop(tmp_path / mode, data=iter(batches))
        losses[mode] = [loop.run_step(b)["loss"] for b in batches]
        monkeypatch.undo()
    a = jax.device_get(losses["rules"])
    b = jax.device_get(losses["legacy"])
    assert [float(x) for x in a] == [float(x) for x in b]


# ------------------------------------------------------- shard/gather fns


def test_make_shard_and_gather_fns_roundtrip():
    mesh = make_mesh(dp=8)
    tree = {"w": np.arange(64, dtype=np.float32).reshape(16, 4),
            "b": np.ones((3,), np.float32)}  # 3 does not divide 8
    specs = pt.match_partition_rules(
        (((r"w$", P("data", None))), (r".*", P())), tree)
    shard_fns, gather_fns = pt.make_shard_and_gather_fns(mesh, specs)
    sharded = {k: shard_fns[k](v) for k, v in tree.items()}
    assert sharded["w"].sharding.spec == P("data", None)
    # divisibility fallback: replicated (spec spelling may pad with None)
    assert sharded["b"].sharding.spec in (P(), P(None))
    gathered = {k: gather_fns[k](v) for k, v in sharded.items()}
    for k in tree:
        assert gathered[k].sharding.spec == P()
        np.testing.assert_array_equal(np.asarray(gathered[k]), tree[k])


# --------------------------------------------------------------- ZeRO-1


def test_zero1_spec_placement():
    mesh = make_mesh(dp=2, fsdp=2, tensor=2)
    # free dim first
    assert pt.zero1_spec(mesh, P("fsdp", None), (8, 8)) == P("fsdp", "data")
    # no free dim: extend an already-sharded dim
    assert pt.zero1_spec(mesh, P("fsdp", "tensor"), (8, 8)) == \
        P(("fsdp", "data"), "tensor")
    # nothing divides: unchanged
    assert pt.zero1_spec(mesh, P(None,), (3,)) == P(None)
    # scalars untouched
    assert pt.zero1_spec(mesh, P(), ()) == P()
    # a rule table that already spends the data axis: leaf is dp-sharded
    # as-is — extending again would build an invalid duplicate-axis spec
    assert pt.zero1_spec(mesh, P("data", None), (4, 4)) == P("data", None)
    assert pt.zero1_spec(mesh, P(("fsdp", "data"), None), (8, 4)) == \
        P(("fsdp", "data"), None)


def test_zero1_with_data_sharded_rule_table(tmp_path):
    """--shard_optimizer composes with a rule table that itself shards a
    param over 'data': the already-dp-sharded leaf passes through instead
    of crashing NamedSharding construction with a duplicate axis."""
    rules = pt.parse_partition_rules(
        '[["word_emb/embedding$", ["data", null]], [".*", []]]')
    loop = make_loop(tmp_path, partition_rules=rules, shard_optimizer=True)
    emb = loop.state.params["params"]["word_emb"]["embedding"]
    assert emb.sharding.spec == P("data", None)
    loop.run_step(next(loop.data))


def test_zero1_bit_identical_losses_and_memory_drop(tmp_path):
    """--shard_optimizer must not change the math: per-step losses are
    bit-identical to the unsharded path over the deterministic horizon
    (params may differ by 1 ulp from XLA fusion rounding between the two
    programs — the curves stay numerically together) while per-replica
    optimizer AND EMA bytes drop by ~dp (8 here)."""
    batches = [next(tiny_data("gpt2", 8, seed=1)) for _ in range(8)]
    loops = {s: make_loop(tmp_path / str(s), data=iter(batches),
                          shard_optimizer=s) for s in (False, True)}
    losses = {s: [lp.run_step(b)["loss"] for b in batches]
              for s, lp in loops.items()}
    off = [float(x) for x in jax.device_get(losses[False])]
    on = [float(x) for x in jax.device_get(losses[True])]
    # Bit-identical over the leading horizon; past it the 1-ulp param
    # wobble (FMA/fusion rounding differs between the two XLA programs)
    # can flip a loss bit, so the tail is pinned to closeness instead.
    assert off[:4] == on[:4]
    np.testing.assert_allclose(off, on, rtol=2e-5)
    pa = jax.tree_util.tree_leaves(loops[False].state.params)
    pb = jax.tree_util.tree_leaves(loops[True].state.params)
    for x, y in zip(pa, pb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=0, atol=1e-6)
    fp_off = loops[False].footprint()
    fp_on = loops[True].footprint()
    # logical bytes identical; per-replica bytes ~/ dp (count scalar and
    # any non-dividing leaf stay replicated, so "close to 8x")
    assert fp_on["opt_state_bytes"] == fp_off["opt_state_bytes"]
    assert fp_off["opt_state_bytes_per_replica"] \
        > 4 * fp_on["opt_state_bytes_per_replica"]
    assert fp_off["ema_bytes_per_replica"] \
        > 4 * fp_on["ema_bytes_per_replica"]
    # params keep their layout: ZeRO-1 shards the weight-UPDATE state only
    assert fp_on["params_bytes_per_replica"] == \
        fp_off["params_bytes_per_replica"]


def test_zero1_state_shardings_are_data_sharded(tmp_path):
    loop = make_loop(tmp_path, shard_optimizer=True)
    mu = loop.state.opt_state[0].mu
    specs = {pt.tree_path_name(p): l.sharding.spec
             for p, l in jax.tree_util.tree_flatten_with_path(mu)[0]}
    assert any("data" in str(s) for s in specs.values())
    # the count scalar stays replicated
    assert loop.state.opt_state[0].count.sharding.spec == P()
    for tree in loop.state.ema.values():
        leaf = jax.tree_util.tree_leaves(tree)[0]
        assert "data" in str(leaf.sharding.spec)


def test_zero1_checkpoint_roundtrip_exact_resume(tmp_path):
    """save -> restore -> continue must be bit-identical to the
    uninterrupted ZeRO run: the sharded optimizer/EMA companions round-
    trip through orbax in their sharded layout (same program resumes, so
    exact equality — the satellite's acceptance)."""
    batches = [next(tiny_data("gpt2", 8, seed=2)) for _ in range(6)]
    gold = make_loop(tmp_path / "gold", data=iter(batches),
                     shard_optimizer=True)
    for b in batches:
        gold.run_step(b)

    part = make_loop(tmp_path / "run", data=iter(batches[:3]),
                     shard_optimizer=True)
    for b in batches[:3]:
        part.run_step(b)
    part.save(wait=True)

    resumed = make_loop(tmp_path / "run", data=iter(batches[3:]),
                        shard_optimizer=True)
    assert resumed.step == 3
    for b in batches[3:]:
        m = resumed.run_step(b)
    del m
    for name in ("params", "opt_state", "ema"):
        ga = jax.tree_util.tree_leaves(getattr(gold.state, name))
        ra = jax.tree_util.tree_leaves(getattr(resumed.state, name))
        for x, y in zip(ga, ra):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=name)
    # restored state keeps the ZeRO layout (no silent re-replication)
    fp = resumed.footprint()
    assert fp["opt_state_bytes"] > 4 * fp["opt_state_bytes_per_replica"]


def test_zero1_restore_flag_flip_both_directions(tmp_path):
    """Checkpoints restore across a --shard_optimizer flip in either
    direction: orbax reshards into whatever layout the abstract target
    asks for (sharded run resumes an unsharded checkpoint and vice
    versa), so the flag is a per-run choice, not a run-dir property."""
    batches = [next(tiny_data("gpt2", 8, seed=4)) for _ in range(4)]
    a = make_loop(tmp_path, data=iter(batches[:2]), shard_optimizer=False)
    for b in batches[:2]:
        a.run_step(b)
    a.save(wait=True)
    b_loop = make_loop(tmp_path, data=iter(batches[2:]),
                       shard_optimizer=True)
    assert b_loop.step == 2
    oa = jax.tree_util.tree_leaves(a.state.opt_state)
    ob = jax.tree_util.tree_leaves(b_loop.state.opt_state)
    for x, y in zip(oa, ob):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for b in batches[2:]:
        b_loop.run_step(b)
    b_loop.save(wait=True)
    c = make_loop(tmp_path, data=tiny_data("gpt2", 8, seed=4),
                  shard_optimizer=False)
    assert c.step == 4
    oc = jax.tree_util.tree_leaves(c.state.opt_state)
    od = jax.tree_util.tree_leaves(b_loop.state.opt_state)
    for x, y in zip(oc, od):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_zero1_walkback_past_corrupt_newest_with_sharded_companions(
        tmp_path):
    """The r10 corrupt-newest recovery with ZeRO-sharded companions: a
    garbled newest checkpoint walks the restore back to the older
    finalized step, whose sharded opt/EMA companions load in the ZeRO
    layout, and training continues."""
    from distributed_pipeline_tpu.chaos import corrupt_newest_checkpoint

    loop = make_loop(tmp_path, shard_optimizer=True)
    for _ in range(2):
        loop.run_step(next(loop.data))
    loop.save(wait=True)
    for _ in range(2):
        loop.run_step(next(loop.data))
    loop.save(wait=True)
    victim = corrupt_newest_checkpoint(str(tmp_path))
    assert victim and "000004" in victim
    resumed = make_loop(tmp_path, shard_optimizer=True)
    assert resumed.step == 2
    assert resumed.resumed_from.endswith("model_000002")
    # companions restored (not degraded): opt state matches the step-2
    # snapshot the original loop saved, in the sharded layout
    fp = resumed.footprint()
    assert fp["opt_state_bytes"] > 4 * fp["opt_state_bytes_per_replica"]
    resumed.run_step(next(resumed.data))  # sharded state dispatches fine


def test_zero1_missing_ema_companion_degrades_into_zero_layout(tmp_path):
    """A missing EMA companion seeds from params — but must land in the
    ZeRO (data-sharded) layout: the AOT step pins its state shardings,
    so a params-layout EMA would be rejected at the second step."""
    import shutil

    loop = make_loop(tmp_path, shard_optimizer=True)
    loop.run_step(next(loop.data))
    loop.save(wait=True)
    shutil.rmtree(tmp_path / "ema_0.9_000001")
    resumed = make_loop(tmp_path, shard_optimizer=True)
    assert resumed.step == 1
    ema_leaf = jax.tree_util.tree_leaves(resumed.state.ema["0.9"])[0]
    assert "data" in str(ema_leaf.sharding.spec)
    # two steps: the second dispatch is the one a mislaid layout breaks
    resumed.run_step(next(resumed.data))
    resumed.run_step(next(resumed.data))
    assert resumed.steady_recompile_count == 0


def test_partition_rules_override_reaches_trainloop(tmp_path):
    """--partition_rules replaces the model's table: an everything-
    replicated override must leave every param leaf unsharded on a mesh
    that would otherwise fsdp-shard them."""
    mesh = make_mesh(dp=1, fsdp=8)
    loop = make_loop(tmp_path, mesh=mesh,
                     partition_rules=pt.parse_partition_rules('[[".*", []]]'))
    for leaf in jax.tree_util.tree_leaves(loop.state.params):
        assert leaf.sharding.spec == P(*(None,) * np.ndim(leaf)) \
            or leaf.sharding.spec == P()
    # and the default (no override) DOES shard on this mesh
    loop2 = make_loop(tmp_path / "default", mesh=mesh)
    assert any("fsdp" in str(l.sharding.spec)
               for l in jax.tree_util.tree_leaves(loop2.state.params))
