"""Pallas fast-path parity suite (ISSUE 18): the flash-decode kernel
against the XLA gather path across page geometries (partial last pages,
trash-routed dead slots, prefix-cache shared pages), the DecodeServer
greedy-token identity + frozen-steady-compile contract under
``decode_impl="pallas"``, the fused AdamW+EMA update's bit-parity with the
staged optax chain (unsharded AND composed with ZeRO-1), the vocab-parallel
cross-entropy decomposition, and the schedule-derived HBM byte accounting
both bench legs land. Off-TPU the kernels run in Pallas interpreter mode —
same kernel logic, tier-1 speed."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from distributed_pipeline_tpu.data import load_data_from_args
from distributed_pipeline_tpu.models import create_model_from_config
from distributed_pipeline_tpu.ops.flash_decode import (
    decode_hbm_bytes,
    flash_decode,
    paged_decode_attention,
    resolve_decode_impl,
    xla_paged_decode,
)
from distributed_pipeline_tpu.ops.fused_update import (
    fused_adamw_ema,
    update_hbm_bytes,
)
from distributed_pipeline_tpu.ops.xent import token_cross_entropy
from distributed_pipeline_tpu.parallel import make_mesh
from distributed_pipeline_tpu.serving import TRASH_PAGE, DecodeServer
from distributed_pipeline_tpu.utils.trainer import TrainLoop

# ----------------------------------------------------------- flash-decode


def paged_case(rng, *, slots, n_pages, page_size, n_heads, head_dim,
               positions, table=None):
    """Random pool + block tables; page 0 is the trash page and is filled
    with large garbage so any accidental read of it shows up loudly."""
    P = 1 + slots * n_pages
    k = rng.standard_normal((P, page_size, n_heads, head_dim))
    v = rng.standard_normal((P, page_size, n_heads, head_dim))
    k[TRASH_PAGE] = 37.0
    v[TRASH_PAGE] = -53.0
    if table is None:
        table = 1 + np.arange(slots * n_pages).reshape(slots, n_pages)
    q = rng.standard_normal((slots, n_heads, head_dim))
    return (jnp.asarray(q, jnp.float32), jnp.asarray(k, jnp.float32),
            jnp.asarray(v, jnp.float32), jnp.asarray(table, jnp.int32),
            jnp.asarray(positions, jnp.int32))


def dense_reference(q, k_pool, v_pool, table, positions):
    """Straight-line numpy softmax over each slot's live prefix only."""
    q, k_pool, v_pool = map(np.asarray, (q, k_pool, v_pool))
    table, positions = np.asarray(table), np.asarray(positions)
    B, H, Dh = q.shape
    ps = k_pool.shape[1]
    out = np.zeros_like(q)
    for b in range(B):
        n_live = positions[b] + 1
        ks = np.concatenate([k_pool[p] for p in table[b]], 0)[:n_live]
        vs = np.concatenate([v_pool[p] for p in table[b]], 0)[:n_live]
        for h in range(H):
            s = ks[:, h] @ q[b, h] * Dh ** -0.5
            p = np.exp(s - s.max())
            out[b, h] = (p / p.sum()) @ vs[:, h]
    return out


@pytest.mark.parametrize("page_size,n_pages,positions", [
    (4, 4, [0, 3, 7, 15]),      # empty-but-one, exact page edge, full
    (2, 8, [1, 4, 9, 14]),      # many small pages, interior positions
    (8, 2, [2, 5, 8, 12]),      # partial first page / spilled second
])
def test_flash_decode_matches_xla_across_geometries(page_size, n_pages,
                                                    positions):
    rng = np.random.default_rng(7)
    q, k, v, bt, pos = paged_case(
        rng, slots=4, n_pages=n_pages, page_size=page_size, n_heads=2,
        head_dim=8, positions=positions)
    got = np.asarray(flash_decode(q, k, v, bt, pos))
    ref = np.asarray(xla_paged_decode(q, k, v, bt, pos))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)
    np.testing.assert_allclose(got, dense_reference(q, k, v, bt, pos),
                               rtol=2e-5, atol=2e-6)


def test_flash_decode_ignores_dead_pages_and_garbage_tails():
    """Entries past the live prefix of a block-table row may be anything
    (contract): point them at the garbage trash page and poison the dead
    rows of each last live page — the output must not move."""
    rng = np.random.default_rng(11)
    ps, n = 4, 4
    q, k, v, bt, pos = paged_case(rng, slots=3, n_pages=n, page_size=ps,
                                  n_heads=2, head_dim=8,
                                  positions=[1, 5, 9])
    clean = np.asarray(flash_decode(q, k, v, bt, pos))
    btp = np.asarray(bt).copy()
    kp, vp = np.asarray(k).copy(), np.asarray(v).copy()
    for b, p in enumerate(np.asarray(pos)):
        btp[b, p // ps + 1:] = TRASH_PAGE          # dead table tail
        last = btp[b, p // ps]
        kp[last, p % ps + 1:] = 1e4                 # dead rows in last page
        vp[last, p % ps + 1:] = -1e4
    got = np.asarray(flash_decode(q, jnp.asarray(kp), jnp.asarray(vp),
                                  jnp.asarray(btp), pos))
    np.testing.assert_array_equal(got, clean)


def test_flash_decode_prefix_cache_shared_pages():
    """Two slots listing the SAME physical page (PrefixCache sharing) just
    schedule two reads of it — parity must hold with divergent tails."""
    rng = np.random.default_rng(13)
    q, k, v, bt, pos = paged_case(
        rng, slots=2, n_pages=3, page_size=4, n_heads=2, head_dim=8,
        positions=[6, 10],
        table=np.asarray([[1, 2, 3], [1, 4, 5]]))  # page 1 shared head
    got = np.asarray(flash_decode(q, k, v, bt, pos))
    ref = np.asarray(xla_paged_decode(q, k, v, bt, pos))
    np.testing.assert_allclose(got, ref, rtol=2e-5, atol=2e-6)


def test_flash_decode_under_jit_and_seam_dispatch():
    """The seam is called from inside the engine's jitted decode step:
    tracing must work and forced impls must agree through it."""
    rng = np.random.default_rng(17)
    q, k, v, bt, pos = paged_case(rng, slots=2, n_pages=2, page_size=4,
                                  n_heads=2, head_dim=8, positions=[3, 6])
    f = jax.jit(functools.partial(paged_decode_attention, impl="pallas"))
    g = jax.jit(functools.partial(paged_decode_attention, impl="xla"))
    np.testing.assert_allclose(np.asarray(f(q, k, v, bt, pos)),
                               np.asarray(g(q, k, v, bt, pos)),
                               rtol=2e-5, atol=2e-6)


def test_resolve_decode_impl_dispatch():
    assert resolve_decode_impl("pallas") == "pallas"   # forced passes through
    assert resolve_decode_impl("xla") == "xla"
    if jax.default_backend() != "tpu":
        assert resolve_decode_impl("auto") == "xla"    # no TPU -> gather path
    with pytest.raises(ValueError, match="auto|pallas|xla"):
        resolve_decode_impl("cuda")


def test_decode_hbm_bytes_counts_live_pages_only():
    """The byte model is the schedule: live pages x (K+V), consecutive
    duplicates free, q/out per slot, step table — and it must scale with
    POSITION, not the page reservation."""
    ps, H, Dh = 4, 2, 8
    bt = np.asarray([[1, 2, 3], [4, 5, 6]])
    page = ps * H * Dh * 4
    qo = H * Dh * 4
    tab = 2 * 3 * 7 * 4
    got = decode_hbm_bytes(bt, np.asarray([0, 5]), ps, H, Dh)
    # slot 0: 1 live page; slot 1: 2 live pages -> 3 distinct page visits
    assert got == 3 * 2 * page + 2 * 2 * qo + tab
    # growing the reservation (dead tail) must not move the number
    bt_wide = np.concatenate([bt, np.full((2, 5), TRASH_PAGE)], 1)
    wide = decode_hbm_bytes(bt_wide, np.asarray([0, 5]), ps, H, Dh)
    assert wide == got + 2 * 5 * 7 * 4             # only the table grows
    # consecutive identical pages (packed dead runs on TPU) are deducted
    shared = decode_hbm_bytes(np.asarray([[1, 1]]), np.asarray([7]),
                              ps, H, Dh)
    assert shared == 1 * 2 * page + 2 * qo + 2 * 7 * 4


def test_decode_hbm_bytes_dedups_shared_pages_across_slots():
    """ISSUE 20 satellite: dedup is by page-id SET across the whole
    schedule, not consecutive visits — a PrefixCache page shared by every
    slot is DMAd once. Hand count: slots [[1,2],[1,3]] both full — the
    pre-r22 consecutive-only dedup priced page 1 twice (4 page visits);
    the set census prices the 3 distinct pages."""
    ps, H, Dh = 4, 2, 8
    page = ps * H * Dh * 4
    qo = H * Dh * 4
    bt = np.asarray([[1, 2], [1, 3]])
    got = decode_hbm_bytes(bt, np.asarray([7, 7]), ps, H, Dh)
    assert got == 3 * 2 * page + 2 * 2 * qo + 2 * 2 * 7 * 4
    # int8 pool: pages priced at 1 byte/elt, q/out stay fp, table widens
    # to 9 columns for the per-page scale pair
    q8 = decode_hbm_bytes(bt, np.asarray([7, 7]), ps, H, Dh,
                          quantized=True)
    assert q8 == 3 * 2 * (ps * H * Dh) + 2 * 2 * qo + 2 * 2 * 9 * 4


# ------------------------------------------- DecodeServer token identity

VOCAB, SEQ = 32, 16


@pytest.fixture(scope="module")
def serve_wl_params():
    wl = create_model_from_config(
        model_family="gpt2", vocab_size=VOCAB, seq_len=SEQ, hidden_size=32,
        num_layers=2, num_heads=2, dtype="float32")
    return wl, wl.init_params(jax.random.PRNGKey(3))


def test_decode_server_greedy_identical_pallas_vs_xla(serve_wl_params):
    """ISSUE 18 acceptance: greedy decode through the flash-decode kernel is
    token-for-token identical to the XLA paged path, and the kernel arm
    keeps the compile-exactly-once steady contract."""
    wl, params = serve_wl_params
    rng = np.random.default_rng(5)
    prompts = [rng.integers(4, VOCAB, (int(rng.integers(1, 8)),)).astype(
        np.int32) for _ in range(5)]
    outs, steady = {}, {}
    for impl in ("pallas", "xla"):
        srv = DecodeServer(wl, params, decode_slots=2, page_size=4,
                           max_prompt_len=8, max_len=SEQ, seed=0,
                           sanitize=True, decode_impl=impl)
        warm = srv.submit(prompts[0], max_new_tokens=2)
        srv.drain()
        after_warm = srv.recompile_count
        reqs = [warm] + [srv.submit(p, max_new_tokens=2 + i % 4)
                         for i, p in enumerate(prompts[1:])]
        srv.drain()
        outs[impl] = [r.tokens for r in reqs]
        steady[impl] = srv.recompile_count - after_warm
        assert srv.free_slots == 2
        assert srv.mgr.free_pages == srv.mgr.capacity
    assert outs["pallas"] == outs["xla"]
    assert steady["pallas"] == 0, \
        "flash-decode arm recompiled in steady state"
    assert steady["xla"] == 0


# ----------------------------------------------------------- fused update


def tiny_data(batch_size=8, seed=0):
    return load_data_from_args("train", batch_size=batch_size,
                               dataset="synthetic-lm", seq_len=16,
                               vocab_size=64, seed=seed)


def make_loop(tmp_path, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("lr", 1e-3)
    kw.setdefault("learning_steps", 1000)   # schedule state exercised
    kw.setdefault("log_interval", 10 ** 9)
    kw.setdefault("save_interval", 10 ** 9)
    kw.setdefault("mesh", make_mesh(dp=8))
    kw.setdefault("ema_rate", "0.9")
    kw.setdefault("seed", 5)
    data = kw.pop("data", None) or tiny_data(kw["batch_size"])
    wl = create_model_from_config(
        model_family="gpt2", vocab_size=64, seq_len=16, hidden_size=32,
        num_layers=2, num_heads=2, dtype="float32")
    return TrainLoop(model=wl, data=data, checkpoint_dir=str(tmp_path), **kw)


def test_fused_adamw_ema_matches_optax_chain():
    """One direct call against the staged optax chain on a random pytree:
    counts bit-identical; params, moments and EMA copies within 1 ulp
    (eager optax runs op-by-op while the kernel body compiles as one fused
    program, so FMA contraction may round a multiply-add once — inside the
    trainer BOTH paths are jitted and the losses are bitwise over the
    leading horizon, test below)."""
    rng = np.random.default_rng(23)
    lr, wd = 3e-3, 0.01
    params = {"w": jnp.asarray(rng.standard_normal((17, 9)), jnp.float32),
              "b": jnp.asarray(rng.standard_normal((9,)), jnp.float32)}
    grads = jax.tree_util.tree_map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        params)
    opt = optax.adamw(lr, weight_decay=wd)
    state = opt.init(params)
    rates = {"0.9": params, "0.99": params}
    for _ in range(3):   # a few steps so counts/bias corrections move
        upd, state_ref = opt.update(grads, state, params)
        p_ref = optax.apply_updates(params, upd)
        e_ref = {r: jax.tree_util.tree_map(
            lambda e, p: e * float(r) + p * (1 - float(r)), rates[r], p_ref)
            for r in rates}
        p_f, state_f, e_f = fused_adamw_ema(
            params, grads, state, rates,
            lr_fn=lambda _c: jnp.asarray(lr, jnp.float32), weight_decay=wd)
        assert int(state_f[0].count) == int(state_ref[0].count)
        for a, b in zip(jax.tree_util.tree_leaves((p_ref, state_ref, e_ref)),
                        jax.tree_util.tree_leaves((p_f, state_f, e_f))):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-7, atol=3e-7)
        params, state, rates = p_f, state_f, e_f


@pytest.mark.parametrize("zero1", [False, True],
                         ids=["unsharded", "zero1"])
def test_fused_trainer_losses_bit_identical(tmp_path, zero1):
    """ISSUE 18 acceptance: --fused_update must not change the math — the
    loss curve is bit-identical to the optax path over the leading horizon
    (the tail is pinned to closeness for the same 1-ulp fusion-rounding
    reason as the ZeRO-1 precedent), composed with --shard_optimizer in
    the second leg, where the per-replica state sharding must survive."""
    batches = [next(tiny_data(8, seed=1)) for _ in range(8)]
    loops = {f: make_loop(tmp_path / str(f), data=iter(batches),
                          shard_optimizer=zero1, fused_update=f)
             for f in (False, True)}
    losses = {f: [lp.run_step(b)["loss"] for b in batches]
              for f, lp in loops.items()}
    off = [float(x) for x in jax.device_get(losses[False])]
    on = [float(x) for x in jax.device_get(losses[True])]
    assert off[:4] == on[:4]
    np.testing.assert_allclose(off, on, rtol=2e-5)
    if zero1:  # fused path must keep the ZeRO layout, not regather it
        fp_f = loops[True].footprint()
        fp_o = loops[False].footprint()
        assert fp_f["opt_state_bytes_per_replica"] == \
            fp_o["opt_state_bytes_per_replica"]
        assert fp_f["ema_bytes_per_replica"] == \
            fp_o["ema_bytes_per_replica"]


def test_update_hbm_bytes_census():
    """(4+R) reads + (3+R) writes of every leaf plus the scalar row — the
    kernel-arm number the fusedupd bench leg lands."""
    params = {"a": jnp.zeros((10, 3)), "b": jnp.zeros((7,))}
    R, db = 2, 4
    got = update_hbm_bytes(params, n_ema_rates=R, dtype_bytes=db)
    assert got == sum((7 + 2 * R) * n * db + 3 * 4 * 128 for n in (30, 7))


# ------------------------------------------------------ vocab-parallel CE


@pytest.mark.parametrize("tp", [2, 4])
def test_vocab_parallel_xent_matches_replicated(tp):
    """The Megatron-style decomposition over vocab shards must reproduce
    the single-device NLL for targets owned by every shard (vmap with an
    axis name stands in for the tensor mesh axis — same collectives)."""
    rng = np.random.default_rng(29)
    B, T, V = 3, 5, 8 * tp
    logits = jnp.asarray(rng.standard_normal((B, T, V)), jnp.float32)
    targets = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    ref = token_cross_entropy(logits, targets)
    shards = jnp.moveaxis(logits.reshape(B, T, tp, V // tp), 2, 0)
    got = jax.vmap(lambda l: token_cross_entropy(l, targets, axis_name="tp"),
                   axis_name="tp")(shards)
    for r in range(tp):  # identical on every rank, equal to the dense NLL
        np.testing.assert_allclose(np.asarray(got[r]), np.asarray(ref),
                                   rtol=1e-6, atol=1e-6)


def test_vocab_parallel_xent_bf16_inputs():
    """bf16 logits: statistics accumulate in f32 on both paths, so the
    sharded result tracks the replicated one at bf16 resolution."""
    rng = np.random.default_rng(31)
    B, T, V, tp = 2, 4, 16, 4
    logits = jnp.asarray(rng.standard_normal((B, T, V)),
                         jnp.bfloat16)
    targets = jnp.asarray(rng.integers(0, V, (B, T)), jnp.int32)
    ref = token_cross_entropy(logits, targets)
    shards = jnp.moveaxis(logits.reshape(B, T, tp, V // tp), 2, 0)
    got = jax.vmap(lambda l: token_cross_entropy(l, targets, axis_name="tp"),
                   axis_name="tp")(shards)
    assert bool(jnp.all(jnp.isfinite(got)))
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
