"""Data pipeline tests (SURVEY.md §4 recommends covering the loader contract
the reference never tested)."""

import json
import os

import numpy as np
import pytest

from distributed_pipeline_tpu.data import (
    JsonlSeq2SeqDataset,
    SyntheticLMDataset,
    SyntheticSeq2SeqDataset,
    batch_iterator,
    infinite_loader_from_iterable,
    load_data_from_args,
)
from distributed_pipeline_tpu.data.dataset import BOS_ID, EOS_ID, PAD_ID, SEP_ID


def test_synthetic_seq2seq_shapes_and_masks():
    ds = SyntheticSeq2SeqDataset(seq_len=64, vocab_size=512, seed=3)
    item = ds[17]
    assert item["input_ids"].shape == (64,)
    assert item["input_ids"].dtype == np.int32
    # Framing: BOS first, SEP between src and tgt, EOS ends the target span.
    ids, tm, pm = item["input_ids"], item["input_mask"], item["pad_mask"]
    assert ids[0] == BOS_ID
    assert (tm <= pm).all()  # target span is within real tokens
    assert tm.sum() > 0
    # target mask starts right after SEP
    sep_pos = int(np.argmax(ids == SEP_ID))
    assert tm[sep_pos] == 0 and tm[sep_pos + 1] == 1
    # padding is masked out
    assert (ids[pm == 0] == PAD_ID).all()


def test_synthetic_deterministic_per_index():
    a = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, seed=5)
    b = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, seed=5)
    for i in (0, 9, 999):
        np.testing.assert_array_equal(a[i]["input_ids"], b[i]["input_ids"])


def test_synthetic_task_is_learnable_mapping():
    # target tokens are a deterministic function of the reversed source
    ds = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, seed=1)
    item = ds[4]
    ids, tm = item["input_ids"], item["input_mask"]
    sep = int(np.argmax(ids == SEP_ID))
    src = ids[1:sep]
    tgt = ids[tm.astype(bool)][:-1]  # strip EOS
    lo = 4
    expect = ((src[::-1] - lo + 7) % (128 - lo)) + lo
    np.testing.assert_array_equal(tgt, expect[: len(tgt)])


def test_lm_dataset_structure():
    ds = SyntheticLMDataset(seq_len=48, vocab_size=256, seed=2)
    item = ds[0]
    assert item["input_ids"].shape == (48,)
    assert item["input_mask"].all() and item["pad_mask"].all()


def test_batch_iterator_shapes_and_sharding():
    ds = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, size=64, seed=0)
    # two "hosts" draw disjoint items from the same shuffled order
    it0 = batch_iterator(ds, 4, shuffle=True, seed=9, loop=False,
                         process_index=0, process_count=2)
    it1 = batch_iterator(ds, 4, shuffle=True, seed=9, loop=False,
                         process_index=1, process_count=2)
    b0, b1 = next(it0), next(it1)
    assert b0["input_ids"].shape == (4, 32)
    assert not np.array_equal(b0["input_ids"], b1["input_ids"])


def test_batch_iterator_loop_and_epoch_reshuffle():
    ds = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, size=8, seed=0)
    it = batch_iterator(ds, 8, shuffle=True, seed=1, loop=True)
    e0, e1 = next(it), next(it)
    assert e0["input_ids"].shape == e1["input_ids"].shape
    # same items, different order across epochs
    assert not np.array_equal(e0["input_ids"], e1["input_ids"])
    assert (np.sort(e0["input_ids"].ravel()) == np.sort(e1["input_ids"].ravel())).all()


def test_batch_iterator_prefetch_thread():
    ds = SyntheticSeq2SeqDataset(seq_len=32, vocab_size=128, size=32, seed=0)
    batches = list(batch_iterator(ds, 8, shuffle=False, loop=False,
                                  num_workers=2))
    assert len(batches) == 4


def test_load_data_from_args_infinite():
    it = load_data_from_args("train", batch_size=2, seq_len=32,
                             vocab_size=128, seed=11)
    b = next(it)
    assert set(b) == {"input_ids", "input_mask", "pad_mask"}
    assert b["input_ids"].shape == (2, 32)


def test_load_data_valid_split_is_heldout_and_deterministic():
    tr = load_data_from_args("train", batch_size=2, deterministic=False,
                             seq_len=32, vocab_size=128, seed=11)
    v1 = load_data_from_args("valid", batch_size=2, deterministic=True,
                             seq_len=32, vocab_size=128, seed=11)
    v2 = load_data_from_args("valid", batch_size=2, deterministic=True,
                             seq_len=32, vocab_size=128, seed=11)
    np.testing.assert_array_equal(next(v1)["input_ids"], next(v2)["input_ids"])
    assert not np.array_equal(next(tr)["input_ids"], next(v2)["input_ids"])


def test_jsonl_dataset(tmp_path):
    path = tmp_path / "train.jsonl"
    rows = [{"src": "a b c", "trg": "x y"}, {"src": "hello world", "trg": "ok"}]
    path.write_text("\n".join(json.dumps(r) for r in rows))
    ds = JsonlSeq2SeqDataset(str(tmp_path), "train", seq_len=32, vocab_size=512)
    assert len(ds) == 2
    item = ds[0]
    ids, tm = item["input_ids"], item["input_mask"]
    assert ids[0] == BOS_ID and (ids == SEP_ID).sum() == 1
    assert tm.sum() == 3  # "x y" + EOS
    # hashing tokenizer is stable
    np.testing.assert_array_equal(ids, ds[0]["input_ids"])


def test_jsonl_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        JsonlSeq2SeqDataset(str(tmp_path), "train")


def test_infinite_loader_from_iterable():
    it = infinite_loader_from_iterable([1, 2])
    assert [next(it) for _ in range(5)] == [1, 2, 1, 2, 1]


def test_multi_producer_order_matches_single(tmp_path):
    """num_workers > 1 spawns real producer threads, but batch order must be
    identical to the unprefetched stream (deterministic striping)."""
    from distributed_pipeline_tpu.data import batch_iterator
    from distributed_pipeline_tpu.data.dataset import SyntheticSeq2SeqDataset

    ds = SyntheticSeq2SeqDataset(seq_len=16, vocab_size=64, size=64, seed=3)
    ref = batch_iterator(ds, 8, shuffle=True, seed=5, loop=False,
                         num_workers=0)
    par = batch_iterator(ds, 8, shuffle=True, seed=5, loop=False,
                         num_workers=3)
    ref_batches = list(ref)
    par_batches = list(par)
    assert len(ref_batches) == len(par_batches) == 8
    for a, b in zip(ref_batches, par_batches):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_multi_producer_infinite_loop_prefix():
    from distributed_pipeline_tpu.data import batch_iterator
    from distributed_pipeline_tpu.data.dataset import SyntheticSeq2SeqDataset
    import itertools

    ds = SyntheticSeq2SeqDataset(seq_len=16, vocab_size=64, size=32, seed=0)
    ref = batch_iterator(ds, 8, shuffle=True, seed=1, loop=True, num_workers=0)
    par = batch_iterator(ds, 8, shuffle=True, seed=1, loop=True, num_workers=2)
    for a, b in itertools.islice(zip(ref, par), 10):
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])
    par.close()


def test_jsonl_end_to_end_training(tmp_path):
    """VERDICT r2 weak #4: TRAIN through the jsonl path, not just shape-check
    it — a real vocab.json corpus with a learnable mapping (trg = src words
    reversed) must drive the loss down through the full TrainLoop."""
    import jax
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    words = [f"w{i}" for i in range(20)]
    vocab = {w: 4 + i for i, w in enumerate(words)}  # ids after reserved 0-3
    rng = np.random.default_rng(0)
    rows = []
    for _ in range(256):
        n = int(rng.integers(3, 7))
        src = [words[int(i)] for i in rng.integers(0, len(words), n)]
        rows.append({"src": " ".join(src), "trg": " ".join(src[::-1])})
    (tmp_path / "train.jsonl").write_text(
        "\n".join(json.dumps(r) for r in rows))
    (tmp_path / "vocab.json").write_text(json.dumps(vocab))

    data = load_data_from_args("train", data_dir=str(tmp_path),
                               batch_size=16, seq_len=16, vocab_size=32,
                               seed=0, num_loader_proc=2)
    wl = create_model_from_config(
        model_family="diffuseq", vocab_size=32, seq_len=16, hidden_size=32,
        num_layers=1, num_heads=2, diffusion_steps=50, dtype="float32")
    loop = TrainLoop(model=wl, data=data, batch_size=16, lr=3e-3,
                     ema_rate="0.9", learning_steps=0, log_interval=10 ** 9,
                     save_interval=10 ** 9, mesh=make_mesh(dp=8),
                     checkpoint_dir=str(tmp_path / "ckpt"), seed=0)
    first = float(loop.run_step(next(loop.data))["loss"])
    for _ in range(25):
        last = float(loop.run_step(next(loop.data))["loss"])
    assert np.isfinite(last) and last < first, (first, last)

    # the vocab file was actually consumed (not the hashing fallback):
    # token w0 -> id 4 by construction
    ds = JsonlSeq2SeqDataset(str(tmp_path), "train", seq_len=16,
                             vocab_size=32)
    assert ds.vocab.token_to_id is not None
    assert ds.vocab.encode("w0") == [4]


# ----------------------------------------------- exact-resume fast-forward

def _batches_equal(a, b):
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_skip_batches_matches_consumed_stream():
    """skip_batches=k must land exactly where a fresh stream is after
    consuming k batches (the exact-order resume contract)."""
    ds = SyntheticSeq2SeqDataset(seq_len=16, vocab_size=64, size=40, seed=3)
    fresh = batch_iterator(ds, 8, seed=3)
    for _ in range(7):  # 7 batches x 8 items over a 40-item set: crosses epochs
        next(fresh)
    skipped = batch_iterator(ds, 8, seed=3, skip_batches=7)
    for _ in range(5):
        _batches_equal(next(fresh), next(skipped))


def test_skip_batches_with_workers_and_sharding():
    ds = SyntheticSeq2SeqDataset(seq_len=16, vocab_size=64, size=64, seed=1)
    kw = dict(seed=1, process_index=1, process_count=2, num_workers=3)
    # skip % num_workers != 0 is the regression case: the prefetch
    # consumer's round-robin must start at the resumed batch's worker
    # queue, not queue 0, or every delivery is rotated.
    for skip in (9, 10, 11):
        fresh = batch_iterator(ds, 4, **kw)
        for _ in range(skip):
            next(fresh)
        skipped = batch_iterator(ds, 4, skip_batches=skip, **kw)
        for _ in range(4):
            _batches_equal(next(fresh), next(skipped))


def test_skip_batches_nonloop_exhausts():
    ds = SyntheticSeq2SeqDataset(seq_len=16, vocab_size=64, size=32, seed=0)
    # one epoch = 4 batches of 8; skipping all of them leaves nothing
    it = batch_iterator(ds, 8, seed=0, loop=False, skip_batches=4)
    assert list(it) == []
    # skipping past the epoch entirely is also empty, not an error
    it = batch_iterator(ds, 8, seed=0, loop=False, skip_batches=9)
    assert list(it) == []


@pytest.mark.slow  # heaviest tier: three TrainLoop builds (VERDICT r5 weak
# #3); the fast resume+warm-cache path is covered by test_bench_budget's
# test_aot_compile_metrics_and_cache_hit_path every run
def test_bit_exact_resume(tmp_path):
    """The gold assertion for elastic recovery: interrupt at step 3, resume,
    finish at step 6 -> parameters IDENTICAL to an uninterrupted 6-step run.
    Data order comes from skip_batches, per-step RNG from fold_in(seed,
    step), state from the checkpoint — nothing depends on wall history."""
    import jax

    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    def wl():
        return create_model_from_config(
            model_family="diffuseq", vocab_size=64, seq_len=16,
            hidden_size=32, num_layers=2, num_heads=2, diffusion_steps=50,
            dtype="float32")

    def data(skip=0):
        return load_data_from_args(
            "train", batch_size=8, dataset="synthetic-seq2seq", seq_len=16,
            vocab_size=64, seed=11, skip_batches=skip)

    common = dict(batch_size=8, lr=1e-3, ema_rate="0.9",
                  log_interval=10 ** 9, save_interval=10 ** 9,
                  mesh=make_mesh(dp=8), seed=11)

    # uninterrupted: 6 steps straight through
    a = TrainLoop(model=wl(), data=data(), learning_steps=6,
                  checkpoint_dir=str(tmp_path / "a"), **common)
    for _ in range(6):
        a.run_step(next(a.data))

    # interrupted twin: 3 steps, save, new loop resumes with skipped data
    b1 = TrainLoop(model=wl(), data=data(), learning_steps=6,
                   checkpoint_dir=str(tmp_path / "b"), **common)
    for _ in range(3):
        b1.run_step(next(b1.data))
    b1.save()
    b2 = TrainLoop(model=wl(), data=data(skip=3), learning_steps=6,
                   checkpoint_dir=str(tmp_path / "b"), **common)
    assert b2.step == 3
    for _ in range(3):
        b2.run_step(next(b2.data))

    for x, y in zip(jax.tree_util.tree_leaves(a.state.params),
                    jax.tree_util.tree_leaves(b2.state.params)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    for x, y in zip(jax.tree_util.tree_leaves(a.state.ema["0.9"]),
                    jax.tree_util.tree_leaves(b2.state.ema["0.9"])):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
