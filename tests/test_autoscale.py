"""Autoscaler tests (ISSUE 17): SLO-driven scale decisions (backlog /
p95-TTFT up, drain-first down, hysteresis, cooldown, min/max clamps),
paid_idle accrual + its goodput re-booking (``accounted_frac`` stays 1.0),
prefix-affinity placement units over the real Router, the deterministic
fleet-workload contract (r13 NOTE), and an elastic e2e ring where live
traffic grows and shrinks a real child-process fleet."""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from distributed_pipeline_tpu.chaos import aggregate_serving, goodput
from distributed_pipeline_tpu.config.serve import ServeSettings
from distributed_pipeline_tpu.run.serve import fleet_workload
from distributed_pipeline_tpu.serving.autoscale import AutoScaler
from distributed_pipeline_tpu.serving.router import Router

from tests.test_fleet import (
    FakeReplica,
    _drive,
    _expected_tokens,
    _fake_ckpt,
    _start_fleet,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ============================================================ fakes / rigs

class FakeFleet:
    """Elastic-fleet stand-in: tracks add/stop calls, readiness is
    instant (the real warmup gate is ServingFleet's, not the scaler's)."""

    swap_active = False

    def __init__(self, n):
        self.n = n
        self.added = []
        self.stopped = []

    def ready_replicas(self):
        return [r for r in range(self.n) if r not in self.stopped]

    def alive(self, rid):
        return rid not in self.stopped

    def add_replica(self):
        rid = self.n
        self.n += 1
        self.added.append(rid)
        return rid

    def stop_replica(self, rid):
        self.stopped.append(rid)

    def client(self, rid):
        return FakeReplica(rid)


class FakeSignals:
    """Router stand-in with directly scriptable signals."""

    def __init__(self, rids, journal_path):
        self.clients = {r: FakeReplica(r) for r in rids}
        self.journal_path = journal_path
        self.backlog = 0
        self.ttfts = []
        self._outstanding = {r: 0 for r in rids}
        self._down = set()
        self._draining = set()
        self.retired = []

    def down(self, rid):
        return rid in self._down

    def outstanding(self, rid):
        return self._outstanding.get(rid, 0)

    def recent_ttfts(self, window_s, now=None):
        return list(self.ttfts)

    def draining(self, rid):
        return rid in self._draining

    def set_draining(self, rid, flag):
        (self._draining.add if flag else self._draining.discard)(rid)

    def add_client(self, rid, client):
        self.clients[rid] = client
        self._outstanding.setdefault(rid, 0)

    def retire(self, rid):
        self._down.add(rid)
        self.retired.append(rid)


def _rig(tmp_path, n=2, **kw):
    fleet = FakeFleet(n)
    router = FakeSignals(range(n), str(tmp_path / "journal.jsonl"))
    kw.setdefault("cooldown_s", 0.0)
    scaler = AutoScaler(fleet, router, **kw)
    return fleet, router, scaler


def _journal_events(scaler):
    try:
        with open(scaler.journal_path) as f:
            return [json.loads(line) for line in f if line.strip()]
    except OSError:
        return []


# ======================================================== scale decisions

def test_scale_up_on_backlog_and_journal(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=4,
                                 up_backlog=2.0)
    router.backlog = 5  # > 2.0 * 2 ready
    scaler.step(now=100.0)
    assert fleet.added == [2] and 2 in router.clients
    assert scaler.scale_ups == 1
    ev = _journal_events(scaler)
    assert [e["ev"] for e in ev] == ["scale"]
    assert ev[0]["dir"] == "up" and ev[0]["reason"] == "backlog"
    assert ev[0]["replica"] == 2 and ev[0]["n_active"] == 3


def test_scale_up_on_ttft_p95_breach(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 1, max_replicas=2,
                                 slo_ttft_s=1.0)
    router.ttfts = [0.2, 0.3, 5.0, 6.0, 7.0]  # p95 >> slo
    scaler.step(now=100.0)
    assert fleet.added == [1]
    assert _journal_events(scaler)[0]["reason"] == "ttft_p95"


def test_max_replicas_caps_growth(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=2)
    router.backlog = 100
    scaler.step()
    assert fleet.added == [] and scaler.scale_ups == 0


def test_crash_looping_fleet_does_not_grow_without_bound(tmp_path):
    """A fleet whose replicas are DOWN but still supervised (restart
    budget in hand) is hot — backlog grows, nothing completes — yet it
    still owns max_replicas worth of capacity. Gating scale-up on
    healthy-only replicas spawned a fresh ring every cooldown for as
    long as the outage lasted (caught live: 13 scale-ups with
    max_replicas=2); down-but-alive rings must count toward the cap."""
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=2)
    router.backlog = 100
    router._down = {0, 1}  # e.g. missed heartbeats while crash-looping
    for _ in range(5):
        scaler.step()
    assert fleet.added == [] and scaler.scale_ups == 0


def test_budget_exhausted_replica_frees_scale_up_headroom(tmp_path):
    """The flip side: a replica that is down AND unsupervised (ring dead
    — restart budget exhausted, or drained + retired) no longer counts,
    so the scaler may place a replacement."""
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=2)
    router.backlog = 100
    router._down = {1}
    fleet.stopped.append(1)  # ring is gone for good
    scaler.step()
    assert fleet.added == [2] and scaler.scale_ups == 1


def test_cooldown_spaces_structural_changes(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 1, max_replicas=5,
                                 cooldown_s=60.0)
    router.backlog = 100
    scaler.step()
    scaler.step()
    scaler.step()
    assert fleet.added == [1], "cooldown must clamp to one change"


def test_hysteresis_band_holds_steady(tmp_path):
    """p95 between down_frac*slo and slo with no backlog: neither hot
    nor cold — the band where bursty traffic must not flap the fleet."""
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=4,
                                 slo_ttft_s=10.0, down_frac=0.5)
    router.backlog = 0
    router.ttfts = [7.0] * 10  # 0.5*10 < 7 < 10
    for _ in range(3):
        scaler.step()
    assert fleet.added == [] and fleet.stopped == []
    assert router._draining == set()


def test_scale_down_drains_before_stopping(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 3, max_replicas=4,
                                 min_replicas=1)
    router.ttfts = [0.1]
    router._outstanding = {0: 2, 1: 0, 2: 3}
    scaler.step(now=100.0)
    # victim: the highest-rid IDLE replica — rid 1 (0 and 2 are busy)
    assert router._draining == {1} and fleet.stopped == []
    # a request placed in the same poll round keeps the drain open
    router._outstanding[1] = 1
    scaler.step(now=101.0)
    assert fleet.stopped == []
    router._outstanding[1] = 0
    scaler.step(now=102.0)
    assert fleet.stopped == [1] and router.retired == [1]
    assert scaler.scale_downs == 1
    ev = [e for e in _journal_events(scaler) if e["ev"] == "scale"]
    assert ev[-1]["dir"] == "down" and ev[-1]["replica"] == 1
    assert ev[-1]["drained"] is True and ev[-1]["n_active"] == 2


def test_scale_down_requires_an_idle_victim(tmp_path):
    """Startup shape: p95 is None (nothing completed) and every ready
    replica holds in-flight work — the fleet is busy, not cold, and
    nothing may drain on the empty completion window."""
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=4,
                                 min_replicas=1)
    router._outstanding = {0: 3, 1: 2}
    for _ in range(3):
        scaler.step()
    assert router._draining == set() and fleet.stopped == []


def test_min_replicas_floor_blocks_drain(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 1, max_replicas=4,
                                 min_replicas=1)
    router.ttfts = [0.01]
    for _ in range(3):
        scaler.step()
    assert fleet.stopped == [] and router._draining == set()


def test_drain_timeout_forces_the_stop(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 2, max_replicas=4,
                                 drain_timeout_s=0.0)
    router.ttfts = [0.01]
    router._outstanding = {0: 0, 1: 5}  # only rid 0 is an idle victim;
    # force the timeout path by pinning outstanding after selection
    scaler.step(now=100.0)
    victim = next(iter(router._draining))
    router._outstanding[victim] = 5  # never finishes
    time.sleep(0.01)
    scaler.step(now=101.0)
    assert fleet.stopped == [victim]
    ev = [e for e in _journal_events(scaler) if e["ev"] == "scale"]
    assert ev[-1]["drained"] is False


def test_swap_guard_defers_decisions(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 1, max_replicas=4)
    router.backlog = 100
    fleet.swap_active = True
    scaler.step()
    assert fleet.added == []
    fleet.swap_active = False
    scaler.step()
    assert fleet.added == [1]


def test_validates_bounds(tmp_path):
    with pytest.raises(ValueError, match="min"):
        _rig(tmp_path, 1, min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError, match="down_frac"):
        _rig(tmp_path, 1, down_frac=1.0)


# ============================================================== paid_idle

def test_paid_idle_accrues_to_surplus_replicas_only(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 3, max_replicas=4,
                                 min_replicas=1, cooldown_s=1e9)
    router.backlog = 0
    router.ttfts = [7.0]  # inside the hysteresis band: no scaling
    scaler.step()
    time.sleep(0.05)
    scaler.step()
    scaler.close(now=200.0)
    ev = [e for e in _journal_events(scaler) if e["ev"] == "paid_idle"]
    assert ev, "idle surplus capacity never journaled"
    # charged to the HIGHEST rids beyond the floor (1 and 2, never 0)
    assert {e["replica"] for e in ev} == {1, 2}
    assert all(e["idle_s"] > 0 for e in ev)
    assert scaler.summary()["paid_idle_s"] > 0


def test_paid_idle_not_charged_under_load(tmp_path):
    fleet, router, scaler = _rig(tmp_path, 3, max_replicas=3,
                                 min_replicas=1, cooldown_s=1e9)
    router.backlog = 4  # queue non-empty: capacity is NOT surplus
    scaler.step()
    time.sleep(0.02)
    scaler.step()
    scaler.close()
    assert scaler.summary()["paid_idle_s"] == 0.0
    assert not [e for e in _journal_events(scaler)
                if e["ev"] == "paid_idle"]


def test_aggregate_serving_rebooks_paid_idle(tmp_path):
    """The goodput identity with the new category: paid_idle comes OUT
    of serving (the replica was up, just unneeded), every second still
    lands in exactly one bucket, accounted_frac == 1.0."""
    d = str(tmp_path)
    rd = goodput.replica_dir(d, 0)
    os.makedirs(rd)
    goodput.append_attempt(rd, {
        "attempt": 0, "rc": 0, "t_spawn": 100.0, "t_exit": 110.0,
        "duration_s": 10.0, "downtime_s": 0.0})
    with open(goodput.serving_record_path(rd, 0), "w") as f:
        json.dump({"attempt": 0, "wall_s": 10.0, "serving_s": 9.0,
                   "drain_s": 0.5, "swap_s": 0.5}, f)
    with open(goodput.serving_journal_path(d), "w") as f:
        f.write(json.dumps({"ev": "paid_idle", "replica": 0,
                            "idle_s": 4.0, "t": 105.0}) + "\n")
    agg = aggregate_serving(d)
    assert agg["paid_idle_s"] == pytest.approx(4.0)
    assert agg["serving_s"] == pytest.approx(5.0)  # 9 - 4 re-booked
    assert agg["accounted_frac"] == pytest.approx(1.0)
    # clamp: paid_idle can never exceed what serving has to give
    with open(goodput.serving_journal_path(d), "a") as f:
        f.write(json.dumps({"ev": "paid_idle", "replica": 0,
                            "idle_s": 100.0, "t": 106.0}) + "\n")
    agg = aggregate_serving(d)
    assert agg["serving_s"] == pytest.approx(0.0)
    assert agg["paid_idle_s"] == pytest.approx(9.0)
    assert agg["accounted_frac"] == pytest.approx(1.0)


# ===================================================== affinity placement

def _affinity_router(tmp_path, indices):
    clients = {}
    for rid, idx in indices.items():
        rep = FakeReplica(rid)
        rep.prefix_index = lambda idx=idx: idx
        clients[rid] = rep
    return Router(clients, str(tmp_path / "journal.jsonl"),
                  affinity=True, page_size=4)


def test_affinity_prefers_longest_leading_match(tmp_path):
    prompt = np.asarray([1, 2, 3, 4, 5, 6, 7, 8, 9, 9, 9, 9], np.int32)
    from distributed_pipeline_tpu.serving.transport import (
        prefix_block_hashes)
    h = prefix_block_hashes(prompt, 4)
    router = _affinity_router(tmp_path, {
        0: (),            # cold
        1: h[:1],         # one warm block
        2: h[:2]})        # two warm blocks -> wins despite equal load
    router.submit(prompt, 4)
    router.poll()
    rec = next(iter(router.records.values()))
    assert rec.replica == 2
    assert router.affinity_hits == 1 and router.affinity_placements == 1


def test_affinity_leading_blocks_only(tmp_path):
    """A replica advertising block 2 WITHOUT block 1 scores zero: the
    KV pages only help if the request's pages hit from the start."""
    prompt = np.asarray(list(range(1, 13)), np.int32)
    from distributed_pipeline_tpu.serving.transport import (
        prefix_block_hashes)
    h = prefix_block_hashes(prompt, 4)
    router = _affinity_router(tmp_path, {0: h[1:], 1: ()})
    router.submit(prompt, 4)
    router.poll()
    rec = next(iter(router.records.values()))
    assert rec.replica == 0  # tie at score 0 -> least-loaded order
    assert router.affinity_hits == 0 and router.affinity_placements == 1


def test_affinity_falls_back_to_least_loaded_when_cold(tmp_path):
    prompt = np.asarray([1, 2, 3, 4, 5, 6, 7, 8], np.int32)
    router = _affinity_router(tmp_path, {0: (), 1: ()})
    busy = router.submit(np.asarray([9] * 8, np.int32), 4)
    router.poll()
    router.submit(prompt, 4)
    router.poll()
    recs = sorted(router.records.values(), key=lambda r: r.id)
    assert recs[1].replica != busy.replica  # least-loaded tiebreak
    assert router.affinity_hits == 0


def test_affinity_never_overrides_health_gate(tmp_path):
    prompt = np.asarray([1, 2, 3, 4, 5, 6, 7, 8], np.int32)
    from distributed_pipeline_tpu.serving.transport import (
        prefix_block_hashes)
    h = prefix_block_hashes(prompt, 4)
    router = _affinity_router(tmp_path, {0: h, 1: ()})
    router.clients[0].beacon_age = 1e9  # warm replica is STALE
    router.submit(prompt, 4)
    router.poll()
    rec = next(iter(router.records.values()))
    assert rec.replica == 1, "affinity must lose to the health gate"


def test_affinity_off_keeps_prefixes_empty(tmp_path):
    router = Router({0: FakeReplica(0)},
                    str(tmp_path / "journal.jsonl"))
    rec = router.submit(np.asarray([1, 2, 3, 4], np.int32), 2)
    assert rec.prefix == ()
    router.poll()
    assert router.affinity_placements == 0


# =========================================== fleet workload (r13 NOTE)

def _settings(**kw):
    kw.setdefault("checkpoint_path", "unused")
    kw.setdefault("traffic", "poisson")
    kw.setdefault("seed", 7)
    return ServeSettings(**kw)


def test_fleet_workload_rejects_step_cadence_loudly():
    with pytest.raises(SystemExit, match="arrival_every_steps"):
        fleet_workload(_settings(arrival_every_steps=3), 64, 8)


def test_fleet_workload_prompt_file_order_is_submission_order(tmp_path):
    pf = tmp_path / "prompts.jsonl"
    prompts = [[i + 1, i + 2, i + 3] for i in range(8)]
    with open(pf, "w") as f:
        for p in prompts:
            f.write(json.dumps({"prompt_ids": p}) + "\n")
    gen, reqs = fleet_workload(
        _settings(prompt_file=str(pf), max_new_tokens=4), 64, 8)
    assert [list(map(int, r[1])) for r in reqs] == prompts
    offsets = [r[0] for r in reqs]
    assert offsets == sorted(offsets), "file order must ride sorted offsets"


def test_fleet_workload_deterministic_across_processes(tmp_path):
    """Same seed + prompt file => identical (offset, prompt, mnt) triples
    in a DIFFERENT interpreter — the cross-process determinism contract
    the r13 NOTE demanded for fleet prompt ordering."""
    pf = tmp_path / "prompts.jsonl"
    with open(pf, "w") as f:
        for i in range(6):
            f.write(json.dumps({"prompt_ids": [i + 1, i + 2],
                                "max_new_tokens": 3 + i % 2}) + "\n")
    code = (
        "import json\n"
        "from distributed_pipeline_tpu.config.serve import ServeSettings\n"
        "from distributed_pipeline_tpu.run.serve import fleet_workload\n"
        "s = ServeSettings(checkpoint_path='unused', traffic='bursty',\n"
        f"                  seed=7, prompt_file={json.dumps(str(pf))})\n"
        "_, reqs = fleet_workload(s, 64, 8)\n"
        "print(json.dumps([[t, list(map(int, p)), n]\n"
        "                  for t, p, n in reqs]))\n")
    out = subprocess.run([sys.executable, "-c", code], cwd=REPO,
                         capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    remote = json.loads(out.stdout.strip().splitlines()[-1])
    _, reqs = fleet_workload(
        _settings(traffic="bursty", prompt_file=str(pf)), 64, 8)
    local = [[t, list(map(int, p)), n] for t, p, n in reqs]
    assert remote == local


def test_fleet_workload_synthetic_deterministic():
    a = fleet_workload(_settings(synthetic_requests=5,
                                 shared_prefix_len=4), 64, 8)[1]
    b = fleet_workload(_settings(synthetic_requests=5,
                                 shared_prefix_len=4), 64, 8)[1]
    for (ta, pa, na), (tb, pb, nb) in zip(a, b):
        assert ta == tb and na == nb
        np.testing.assert_array_equal(pa, pb)


# ======================================================= elastic e2e ring

@pytest.mark.chaos
def test_autoscaler_elastic_fleet_e2e(tmp_path):
    """A real child-process fleet under the scaler: a burst grows the
    fleet (the new replica is spawned, becomes ready, serves a second
    traffic wave), the idle tail drains one back down, every request
    completes token-identical, and the ledger (including paid_idle)
    accounts every replica-second."""
    ckpt = tmp_path / "ckpts"
    _fake_ckpt(ckpt, 1, salt=4)
    fleet, router = _start_fleet(tmp_path, 1, ckpt, token_interval=0.05)
    scaler = AutoScaler(fleet, router, min_replicas=1, max_replicas=2,
                        slo_ttft_s=30.0, up_backlog=1.0, down_frac=0.5,
                        cooldown_s=0.2, window_s=60.0, drain_timeout_s=20.0)
    try:
        prompts = [np.arange(i + 1, i + 4, dtype=np.int32)
                   for i in range(8)]
        for p in prompts[:5]:
            router.submit(p, 12)  # burst: backlog >> 1 per ready replica
        scaler.step()  # sees the unplaced backlog -> structural scale-up
        assert scaler.scale_ups == 1

        wave2_sent = False
        deadline = time.time() + 90
        while time.time() < deadline:
            router.poll()
            if not wave2_sent:
                # emulate traffic arriving once the rollout lands: the
                # least-loaded tiebreak steers it to the fresh replica
                if 1 in fleet.ready_replicas() and router.healthy(1):
                    for p in prompts[5:]:
                        router.submit(p, 12)
                    wave2_sent = True
            else:
                scaler.step()
                if (router.all_done() and scaler.scale_downs >= 1
                        and scaler._draining_rid is None):
                    break
            time.sleep(0.02)
    finally:
        scaler.close()
        fleet.stop()
    assert router.completed == 8
    for rec, prompt in zip(sorted(router.records.values(),
                                  key=lambda r: r.id), prompts):
        assert rec.tokens == _expected_tokens(prompt, 12, salt=4)
    assert scaler.scale_ups >= 1, "the burst never grew the fleet"
    assert scaler.scale_downs >= 1, "the idle tail never drained one down"
    assert fleet.n_replicas == 2  # rid 1 was spawned
    # both replicas actually served (the scale-up took traffic)
    assert {r.replica for r in router.records.values()} == {0, 1}
    ev = goodput.read_journal(
        goodput.serving_journal_path(str(tmp_path / "fleet")))
    dirs = [e["dir"] for e in ev if e.get("ev") == "scale"]
    assert "up" in dirs and "down" in dirs
    agg = aggregate_serving(str(tmp_path / "fleet"))
    assert agg["accounted_frac"] == pytest.approx(1.0, abs=0.05)
