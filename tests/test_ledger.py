"""Cost ledger + roofline attribution + regression sentinel (ISSUE 14).

Pins the evidence layer the perf front reads from: cost_analysis/
memory_analysis extraction off CPU-compiled programs, the HLO collective
tally against a hand-counted forced-host dp=2 program, the exact
mfu-plus-gaps-equals-one identity, the ledger-vs-goodput seconds
identity (the ledger reuses the trainer's OWN stall sums — same object,
exact equality), padding-waste arithmetic on both the train and serve
sides, perf_report CLI end-to-end, the obs/regress verdicts over
synthetic (torn-tail-bearing) histories, graftlint GL010, and the
status/export ledger surfaces.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from distributed_pipeline_tpu.obs import ledger as ledger_lib
from distributed_pipeline_tpu.obs import regress as regress_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- HLO tally

def test_hlo_collective_tally_hand_counted_text():
    """Literal HLO text with every op class: single shapes, the async
    -start form (whose result TUPLE leads with the aliased input
    operand — only the result element counts, so sync and async forms
    of the same collective tally identical bytes), its -done twin (not
    counted — it moves no new bytes), and a non-collective line."""
    hlo = "\n".join([
        "%x = f32[8]{0} add(f32[8]{0} %a, f32[8]{0} %b)",
        "ROOT %ar = f32[8]{0} all-reduce(f32[8]{0} %x), channel_id=1",
        # async all-gather: tuple = (input operand, gathered result)
        "%ag = (f32[2,2]{1,0}, f32[4,2]{1,0}) all-gather-start("
        "f32[2,2]{1,0} %p), dimensions={0}",
        "%agd = f32[4,2]{1,0} all-gather-done(%ag)",
        "%rs = bf16[16]{0} reduce-scatter(bf16[32]{0} %y), dimensions={0}",
        # async permute with trailing context elements: still only the
        # result element (position n_operands) counts
        "%cp = (u8[5]{0}, u8[5]{0}, u32[], u32[]) "
        "collective-permute-start(u8[5]{0} %z)",
    ])
    t = ledger_lib.hlo_collective_tally(hlo)
    assert t["counts"] == {"all-reduce": 1, "all-gather": 1,
                           "reduce-scatter": 1, "collective-permute": 1}
    assert t["bytes"]["all-reduce"] == 8 * 4
    assert t["bytes"]["all-gather"] == 4 * 2 * 4  # result only, not the
    # aliased input — the sync form of this op would tally the same
    assert t["bytes"]["reduce-scatter"] == 16 * 2         # bf16
    assert t["bytes"]["collective-permute"] == 5          # u8 result
    assert t["collective_bytes"] == sum(t["bytes"].values())


def test_collective_tally_matches_hand_count_on_real_dp2_program():
    """A compiled program with exactly ONE all-reduce of known shape
    (a [4, 8] f32 sharded over 2 of the forced host devices, summed
    over the sharded axis to a replicated [8]): the tally must report
    exactly 1 x 32 bytes — hand-counted, not pattern-matched."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    from distributed_pipeline_tpu.parallel.partition import (
        resolve_shardings)
    from distributed_pipeline_tpu.parallel.sharding import replicated

    mesh = Mesh(np.array(jax.devices()[:2]), ("data",))
    rep = replicated(mesh)
    dshard = resolve_shardings(
        mesh, P("data"), jax.ShapeDtypeStruct((4, 8), jnp.float32))

    def f(x):
        return jax.lax.with_sharding_constraint((x * 2.0).sum(axis=0), rep)

    x = jax.device_put(jnp.ones((4, 8), jnp.float32), dshard)
    compiled = jax.jit(f).lower(x).compile()
    cost = ledger_lib.extract_cost(compiled)
    assert cost["collectives"]["counts"] == {"all-reduce": 1}
    assert cost["collective_bytes_per_step"] == 8 * 4


def test_extract_cost_fields_on_cpu_compiled_program():
    import jax
    import jax.numpy as jnp

    compiled = jax.jit(lambda x: (x @ x.T).sum()).lower(
        jnp.ones((16, 16), jnp.float32)).compile()
    cost = ledger_lib.extract_cost(compiled)
    assert cost["flops_per_execution"] > 0
    assert cost["bytes_accessed"] > 0
    assert cost["memory"]["argument_bytes"] == 16 * 16 * 4
    assert cost["collective_bytes_per_step"] == 0  # single-device program


def test_extract_cost_never_raises_on_hostile_object():
    class Broken:
        def cost_analysis(self):
            raise RuntimeError("backend says no")

        def memory_analysis(self):
            raise RuntimeError("no")

        def as_text(self):
            raise RuntimeError("no")

    assert ledger_lib.extract_cost(Broken()) == {}


# ------------------------------------------------------ roofline identity

def _ident(row):
    return abs(ledger_lib.gap_sum_identity(row) - 1.0)


def test_roofline_identity_holds_and_components_cap_in_order():
    row = ledger_lib.roofline_attribution(
        tokens_per_s=1e4, flops_per_token=3e5, peak_flops=1e11,
        n_devices=1, steps_per_s=30.0, collective_bytes_per_step=4e5,
        bytes_accessed=8e7, host_stall_s_per_step=0.002,
        device_kind="cpu", padding_waste_frac=0.2)
    assert _ident(row) < 1e-9
    assert 0 < row["mfu"] < 1
    assert all(row[k] >= 0 for k in ledger_lib.GAP_TERMS)
    # host stalls bigger than the whole step: host caps AT the gap and
    # every later (less-trusted) component is squeezed to zero
    capped = ledger_lib.roofline_attribution(
        tokens_per_s=1e4, flops_per_token=3e5, peak_flops=1e11,
        n_devices=1, steps_per_s=30.0, collective_bytes_per_step=1e12,
        bytes_accessed=1e12, host_stall_s_per_step=10.0)
    assert _ident(capped) < 1e-9
    assert capped["mfu_gap_host"] == pytest.approx(1.0 - capped["mfu"])
    assert capped["mfu_gap_comms"] == capped["mfu_gap_memory_bound"] == \
        capped["mfu_gap_residual"] == 0.0


def test_roofline_without_a_step_clock_reports_unattributed():
    """No steps/s -> no modeled component can be estimated: the whole
    gap lands in the residual (reported unattributed, never invented)."""
    row = ledger_lib.roofline_attribution(
        tokens_per_s=0.0, flops_per_token=3e5, peak_flops=1e11,
        n_devices=1, collective_bytes_per_step=4e5, bytes_accessed=8e7,
        padding_waste_frac=2.5)  # clamped too
    assert _ident(row) < 1e-9
    assert row["mfu"] == 0.0 and row["mfu_gap_residual"] == 1.0
    assert row["padding_waste_frac"] == 1.0


def test_padding_meter_arithmetic():
    m = ledger_lib.PaddingMeter()
    assert m.frac == 0.0  # no samples: no waste claimed
    m.add(6, 8)
    m.add(2, 8)
    assert m.frac == pytest.approx(1.0 - 8 / 16)


def test_device_bandwidths_match_known_kinds():
    assert ledger_lib.device_bandwidths("TPU v5 lite")["hbm_bytes_per_s"] \
        == 8.1e11
    assert ledger_lib.device_bandwidths("TPU v9x")["hbm_bytes_per_s"] \
        == 1.2e12  # unknown TPU: v4-class
    assert ledger_lib.device_bandwidths("cpu")["ici_bytes_per_s"] == 1e10


# ------------------------------------------- trainer ledger + goodput tie

@pytest.fixture(scope="module")
def ledger_run(tmp_path_factory):
    """One tiny --cost_ledger training run (real run_loop, real
    perf_ledger.json on disk) shared by the trainer-side tests."""
    from distributed_pipeline_tpu.data import load_data_from_args
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.utils import logger
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    run_dir = str(tmp_path_factory.mktemp("ledger_run"))
    wl = create_model_from_config(
        model_family="diffuseq", vocab_size=64, seq_len=32, hidden_size=32,
        num_layers=2, num_heads=2, dtype="float32", diffusion_steps=50)
    data = load_data_from_args(
        "train", batch_size=8, dataset="synthetic-seq2seq", seq_len=32,
        vocab_size=64, seed=0)
    loop = TrainLoop(model=wl, data=data, batch_size=8, lr=1e-3,
                     ema_rate="0.9", learning_steps=5, log_interval=2,
                     save_interval=10 ** 9, mesh=make_mesh(dp=-1),
                     checkpoint_dir=run_dir, seed=0, cost_ledger=True,
                     dispatch_lag=1)
    with logger.scoped_configure(dir=run_dir, format_strs=[]):
        loop.run_loop()
    return loop, run_dir


def test_trainloop_ledger_row_is_populated(ledger_run):
    loop, _ = ledger_run
    rows = loop.ledger_rows()
    tr = rows["train_step"]
    assert tr["flops_per_execution"] > 0
    assert tr["bytes_accessed"] > 0
    # the 8-fake-device dp mesh really emits gradient collectives
    assert tr["collective_bytes_per_step"] > 0
    assert tr["collectives"]["counts"].get("all-reduce", 0) > 0
    # synthetic-seq2seq pads to seq_len: real waste, strictly inside (0,1)
    assert 0 < tr["padding_waste_frac"] < 1
    assert tr["tokens_per_s"] > 0 and tr["steps_per_s"] > 0
    assert _ident(tr) < 1e-9


def test_ledger_and_goodput_report_the_same_seconds(ledger_run):
    """The ledger's data-stall total is the SAME expression the goodput
    summary folds (one owner: StallBreakdown.sums) — exact equality,
    not approx: the two ledgers can never disagree."""
    loop, _ = ledger_run
    tr = loop.ledger_rows()["train_step"]
    assert tr["data_stall_s_total"] == \
        loop.goodput_summary()["data_stall_s"]


def test_padding_waste_matches_the_masks_the_data_carried(ledger_run):
    """The meter's fraction is exactly 1 - sum(pad_mask)/size over every
    batch _prepare saw."""
    loop, _ = ledger_run
    from distributed_pipeline_tpu.data import load_data_from_args

    data = load_data_from_args(
        "train", batch_size=8, dataset="synthetic-seq2seq", seq_len=32,
        vocab_size=64, seed=0)
    active = total = 0
    for _ in range(loop.step):
        b = next(data)
        active += int(b["pad_mask"].sum())
        total += int(b["pad_mask"].size)
    assert loop.padding.frac == pytest.approx(1.0 - active / total)


def test_perf_ledger_snapshot_written_and_readable(ledger_run):
    _, run_dir = ledger_run
    payload = ledger_lib.read_ledger(run_dir)
    assert payload is not None
    assert payload["step"] == 5
    tr = payload["programs"]["train_step"]
    assert _ident(tr) < 1e-6
    assert "collective_bytes_per_step" in tr


def test_perf_report_cli_end_to_end(ledger_run, tmp_path):
    _, run_dir = ledger_run
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    p = subprocess.run(
        [sys.executable, "-m", "distributed_pipeline_tpu.run.perf_report",
         run_dir], capture_output=True, text=True, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr
    summary = json.loads(p.stdout.strip().splitlines()[-1])
    assert summary["identity_residuals"]["train_step"] < 1e-6
    assert "[train_step]" in p.stderr and "residual" in p.stderr
    # a dir without a ledger exits 2 (a typo'd path must not read as
    # "no gaps")
    p2 = subprocess.run(
        [sys.executable, "-m", "distributed_pipeline_tpu.run.perf_report",
         str(tmp_path)], capture_output=True, text=True, env=env, cwd=REPO)
    assert p2.returncode == 2


def test_status_and_prometheus_surface_the_ledger(ledger_run):
    from distributed_pipeline_tpu.obs import export as export_lib
    from distributed_pipeline_tpu.run.status import render, run_status

    _, run_dir = ledger_run
    snap = run_status(run_dir)
    assert snap["mfu"] is not None
    assert set(snap["mfu_gaps"]) == set(ledger_lib.GAP_TERMS)
    assert "mfu:" in render(snap)
    lines = export_lib.prometheus_lines(run_dir)
    assert any(l.startswith('dpt_mfu{') for l in lines)
    assert any('component="residual"' in l for l in lines)


def test_export_emits_roofline_counter_track(ledger_run):
    from distributed_pipeline_tpu.obs import export as export_lib

    _, run_dir = ledger_run
    trace = export_lib.chrome_trace(run_dir)
    counters = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
    roof = [c for c in counters if c["name"] == "roofline train_step"]
    assert roof, "perf_ledger.json must export as a counter track"
    args = roof[0]["args"]
    assert set(ledger_lib.GAP_TERMS) <= set(args)
    assert all(isinstance(v, float) for v in args.values())


# --------------------------------------------------------- serving ledger

def test_serving_ledger_rows_and_padding_hand_count():
    import jax
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.serving import DecodeServer

    wl = create_model_from_config(
        model_family="gpt2", model_size="base", seq_len=64,
        dtype="float32", hidden_size=32, num_layers=2, num_heads=2,
        vocab_size=64)
    params = wl.init_params(jax.random.PRNGKey(0))
    server = DecodeServer(wl, params, decode_slots=2, page_size=4,
                          max_prompt_len=8, max_len=32)
    rng = np.random.default_rng(0)
    for _ in range(3):
        server.submit(rng.integers(4, 64, (5,)).astype(np.int32),
                      max_new_tokens=6)
    server.drain()
    rows = server.cost_ledger(wall_s=1.0, n_devices=1)
    dec, pre = rows["serve_decode"], rows["serve_prefill"]
    assert _ident(dec) < 1e-9
    assert dec["tokens_per_s"] == server.tokens_fetched  # wall_s=1.0
    assert dec["flops_per_execution"] > 0
    # hand count: 3 prompts of 5 tokens over 2 slots -> 2 prefill
    # dispatches at the compiled [2, 8] shape = 32 token slots, 15 real
    assert server.prefill_steps == 2
    assert pre["padding_waste_frac"] == pytest.approx(1 - 15 / 32)
    # decode occupancy waste: dispatches with one empty slot accrue it
    assert 0 <= dec["padding_waste_frac"] < 1


# ----------------------------------------------------- regression sentinel

def _hist_rows(run_id, tps, mfu=0.5, peak=100, rec=0,
               name="diffuseq-base-seq128"):
    return {"name": name, "tokens_per_sec_per_chip": tps, "mfu": mfu,
            "peak_live_bytes": peak, "recompile_count": rec,
            "run_id": run_id, "t": 1.0}


def _write_history(path, rows, torn_tail=False):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
        if torn_tail:
            f.write('{"name": "torn half li')


def test_regress_verdicts_flat_improved_regressed(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    rows = [_hist_rows("r1", 1000), _hist_rows("r2", 1010),
            # newest: tokens/s inside the band, mfu up 10%, serve leg
            # regressed on recompiles
            _hist_rows("r3", 1005, mfu=0.55)]
    rows.insert(1, _hist_rows("r1", 500, rec=0,
                              name="gpt2-serve-decode-b8"))
    rows.insert(3, _hist_rows("r2", 505, rec=0,
                              name="gpt2-serve-decode-b8"))
    rows.append(_hist_rows("r3", 502, rec=2,
                           name="gpt2-serve-decode-b8"))
    _write_history(hist, rows, torn_tail=True)  # torn tail tolerated
    from distributed_pipeline_tpu.chaos.goodput import read_journal
    runs = regress_lib.group_runs(read_journal(hist))
    assert [rid for rid, _ in runs] == ["r1", "r2", "r3"]
    s = regress_lib.compare_runs(runs, band_pct=3.0, baseline_runs=3)
    train = s["legs"]["diffuseq-base-seq128"]
    assert train["metrics"]["tokens_per_s"]["verdict"] == "flat"
    assert train["metrics"]["mfu"]["verdict"] == "improved"
    assert train["verdict"] == "improved"
    serve = s["legs"]["gpt2-serve-decode-b8"]
    # steady recompiles are a 0-contract: ANY increase regresses
    assert serve["metrics"]["recompile_count"]["verdict"] == "regressed"
    assert serve["verdict"] == "regressed"
    assert s["verdict"] == "regressed" and s["regressed"] == 1


def test_regress_flags_a_leg_that_stopped_producing_data(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    _write_history(hist, [
        _hist_rows("r1", 1000), _hist_rows("r2", 1000),
        {"name": "diffuseq-base-seq128", "error": "LegTimeout: boom",
         "run_id": "r3", "t": 1.0}])
    from distributed_pipeline_tpu.chaos.goodput import read_journal
    s = regress_lib.compare_runs(regress_lib.group_runs(
        read_journal(hist)))
    leg = s["legs"]["diffuseq-base-seq128"]
    assert leg["verdict"] == "regressed" and "errored" in leg["reason"]


def test_regress_budget_skip_is_not_a_regression(tmp_path):
    """A {"skipped": "budget"} marker in the newest run is the bench's
    documented normal mode under BENCH_BUDGET_S — no comparison, never
    a red gate (only an ERROR row regresses against baseline data)."""
    hist = str(tmp_path / "h.jsonl")
    _write_history(hist, [
        _hist_rows("r1", 1000), _hist_rows("r2", 1000),
        {"name": "diffuseq-base-seq128", "skipped": "budget",
         "run_id": "r3", "t": 1.0}])
    s, rc = regress_lib.main(["--history", hist, "--json"])
    assert rc == 0 and s["verdict"] != "regressed"
    assert "diffuseq-base-seq128" not in s["legs"]


def test_regress_insufficient_history_is_honest(tmp_path):
    hist = str(tmp_path / "h.jsonl")
    _write_history(hist, [_hist_rows("r1", 1000)])
    s, rc = regress_lib.main(["--history", hist, "--json"])
    assert rc == 0 and s["verdict"] == "insufficient-history"


def test_regress_main_exit_codes(tmp_path, capsys):
    hist = str(tmp_path / "h.jsonl")
    _write_history(hist, [_hist_rows("r1", 1000), _hist_rows("r2", 1000),
                          _hist_rows("r3", 800)])
    s, rc = regress_lib.main(["--history", hist])
    assert rc == 1 and s["verdict"] == "regressed"
    out = capsys.readouterr()
    assert json.loads(out.out)["verdict"] == "regressed"  # machine line
    assert "regressed" in out.err                         # human table
    _write_history(hist, [_hist_rows("r1", 1000), _hist_rows("r2", 1000),
                          _hist_rows("r3", 1001)])
    _, rc = regress_lib.main(["--history", hist, "--json"])
    assert rc == 0
    capsys.readouterr()


# ----------------------------------------------------------------- GL010

def test_gl010_flags_inline_flops_and_spares_the_owners(tmp_path):
    from distributed_pipeline_tpu.analysis import run_paths

    pos = tmp_path / "pos.py"
    pos.write_text(
        "def f(n, l, h, s, tps):\n"
        "    fpt = 6.0 * n + 12.0 * l * h * s\n"
        "    mfu = tps * fpt / (1e12 * 8)\n"
        "    return {'model_flops': n * 6}, mfu\n")
    neg = tmp_path / "neg.py"
    neg.write_text(
        "from distributed_pipeline_tpu.utils.perf import (\n"
        "    mfu, transformer_train_flops_per_token)\n\n"
        "def f(n, l, h, s, tps):\n"
        "    fpt = transformer_train_flops_per_token(n, l, h, s)\n"
        "    return {'mfu': round(mfu(tps, fpt), 4), 'fpt': fpt}\n")
    findings, n = run_paths([str(pos), str(neg)])
    gl010 = [f for f in findings if f.rule == "GL010-unattributed-flops"]
    assert n == 2
    assert len(gl010) == 3
    assert all(f.path.endswith("pos.py") for f in gl010)
