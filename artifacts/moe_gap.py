"""Decompose the MoE MFU gap (r4 bench: 62.6% moe8 vs 76.5% dense on
diffuseq-base seq128, MFU vs ACTIVE params).

Times ONE MLP sublayer at the bench microbatch shape (B=64, L=128,
D=768, M=4D, E=8, K=2) — dense vs the routed mixture — fwd and
fwd+bwd, long-chain differenced on the real chip (see flash_sweep.py
for the method). Variants:

  dense          backbone.Mlp math (the anchor; active MoE compute
                 = K x this, so `K * dense` is the zero-overhead ideal)
  moe-cf1.25     moe_mlp_fwd at the shipped defaults
  moe-cf1.0      capacity_factor 1.0 (no padding slots beyond K*L)
  moe-cf1.25-k1  top-1 routing (Switch), cf 1.25
  moe-machinery  router + top-k + load-balance aux (F_sum/P_sum) +
                 capacity cumsum + combine/dispatch build ONLY (no
                 expert matmuls): the non-MXU overhead
  moe-bf16comb   fork of moe_mlp_fwd building the [B, L, E, C] combine
                 tensor in bf16 (halves its HBM footprint)

Interpretation key (written up in PARITY.md "MoE" section): with slots
= E*C = K*cf*L, the expert matmuls compute cf x the active flops, so
even a zero-overhead dispatch caps MFU-vs-active at dense_MFU/cf on
the MLP share of the model. The measured rows separate that
algorithmic padding from implementation overhead (dispatch einsums +
routing machinery).
"""
import functools
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_pipeline_tpu.models.moe import moe_mlp_fwd

B, L, D, E = 64, 128, 768, 8
M = 4 * D


def drain(out):
    float(jax.device_get(jnp.sum(jax.tree_util.tree_leaves(out)[0])
                         .astype(jnp.float32)))


def chain_total(step, reps, *args):
    @jax.jit
    def chain(x, mp):
        def body(_, c):
            return step(c, mp)
        return jax.lax.fori_loop(0, reps, body, x)
    drain(chain(*args))
    t0 = time.perf_counter()
    drain(chain(*args))
    return time.perf_counter() - t0


def make_params(key):
    ks = jax.random.split(key, 5)
    init = lambda k, *s: jax.random.normal(k, s, jnp.float32) * 0.02
    return {
        "router": init(ks[0], D, E),
        "wi": init(ks[1], E, D, M), "wo": init(ks[2], E, M, D),
        # dense anchor weights (same fan-in init, independent keys — dwi
        # and dwo from one key would be transposes of the same draw,
        # misleading any numerics comparison against the dense anchor)
        "dwi": init(ks[3], D, M), "dwo": init(ks[4], M, D),
    }


def dense_fwd(mp, x):
    h = jnp.einsum("bld,dm->blm", x, mp["dwi"].astype(jnp.bfloat16))
    h = nn.gelu(h, approximate=True)
    return jnp.einsum("blm,md->bld", h, mp["dwo"].astype(jnp.bfloat16))


def moe_fwd(mp, x, *, top_k, cf):
    sub = {"router": mp["router"], "wi": mp["wi"], "wo": mp["wo"]}
    y, _aux, _ = moe_mlp_fwd(sub, x, None, top_k=top_k,
                             capacity_factor=cf, dtype=jnp.bfloat16)
    return y


def moe_machinery(mp, x, *, top_k, cf):
    """Everything except the expert matmuls: the routing/dispatch
    overhead in isolation. Reimplements moe_mlp_fwd's plan build —
    INCLUDING the Switch load-balance reductions (F_sum/P_sum/aux,
    moe.py:154-165), which the real forward always pays — then
    contracts combine straight against x (one cheap einsum) so nothing
    is DCE'd."""
    import math
    K, C = top_k, max(1, math.ceil(L / E * cf * top_k))
    logits = jnp.einsum("bld,de->ble", x.astype(jnp.float32), mp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    remaining, gates, masks = probs, [], []
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        remaining = remaining * (1.0 - mask)
        gates.append((probs * mask).sum(-1))
        masks.append(mask)
    # Switch aux loss statistics (moe_mlp_fwd computes these every call;
    # no pad mask here, so the live count is just B*L)
    n_live = jnp.asarray(B * L, jnp.float32)
    F_sum = masks[0].sum(axis=(0, 1))
    P_sum = probs.sum(axis=(0, 1))
    aux = E * jnp.sum(F_sum / n_live * (P_sum / n_live))
    claims = jnp.stack(masks, axis=2).reshape(B, L * K, E)
    pos = jnp.cumsum(claims, axis=1) - claims
    keep_flat = claims * (pos < C)
    slot_idx = (pos * keep_flat).sum(-1).astype(jnp.int32)
    slot_flat = jax.nn.one_hot(slot_idx, C, dtype=jnp.float32)
    keep = keep_flat.reshape(B, L, K, E)
    slot = slot_flat.reshape(B, L, K, C)
    kept_gate = [g * keep[:, :, k].sum(-1) for k, g in enumerate(gates)]
    denom = jnp.maximum(sum(kept_gate), 1e-9)
    combine = jnp.zeros((B, L, E, C), jnp.float32)
    for k, g in enumerate(gates):
        w = (g / denom)[..., None] * keep[:, :, k]
        combine = combine + w[..., None] * slot[:, :, k][:, :, None, :]
    # consume the plan AND the aux statistics without the expert MLPs
    return (x + jnp.einsum("blec,bld->bld", combine.astype(x.dtype), x) * 1e-6
            + aux.astype(x.dtype) * 1e-30)


def moe_fwd_bf16comb(mp, x, *, top_k, cf):
    """moe_mlp_fwd fork: combine built directly in bf16."""
    import math
    K, C = top_k, max(1, math.ceil(L / E * cf * top_k))
    logits = jnp.einsum("bld,de->ble", x.astype(jnp.float32), mp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    remaining, gates, masks = probs, [], []
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        remaining = remaining * (1.0 - mask)
        gates.append((probs * mask).sum(-1))
        masks.append(mask)
    claims = jnp.stack(masks, axis=2).reshape(B, L * K, E)
    pos = jnp.cumsum(claims, axis=1) - claims
    keep_flat = claims * (pos < C)
    slot_idx = (pos * keep_flat).sum(-1).astype(jnp.int32)
    slot_flat = jax.nn.one_hot(slot_idx, C, dtype=jnp.float32)
    keep = keep_flat.reshape(B, L, K, E)
    slot = slot_flat.reshape(B, L, K, C)
    kept_gate = [g * keep[:, :, k].sum(-1) for k, g in enumerate(gates)]
    denom = jnp.maximum(sum(kept_gate), 1e-9)
    combine = jnp.zeros((B, L, E, C), jnp.bfloat16)
    for k, g in enumerate(gates):
        w = ((g / denom)[..., None] * keep[:, :, k]).astype(jnp.bfloat16)
        combine = combine + w[..., None] * slot[:, :, k][
            :, :, None, :].astype(jnp.bfloat16)
    dispatch = (combine > 0).astype(jnp.bfloat16)
    xin = jnp.einsum("blec,bld->ebcd", dispatch, x.astype(jnp.bfloat16))
    h = jnp.einsum("ebcd,edm->ebcm", xin, mp["wi"].astype(jnp.bfloat16))
    h = nn.gelu(h, approximate=True)
    out = jnp.einsum("ebcm,emd->ebcd", h, mp["wo"].astype(jnp.bfloat16))
    return jnp.einsum("blec,ebcd->bld", combine, out).astype(x.dtype)


def main():
    mp = make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, D), jnp.bfloat16)

    variants = [
        ("dense", dense_fwd),
        ("moe-cf1.25", functools.partial(moe_fwd, top_k=2, cf=1.25)),
        ("moe-cf1.0", functools.partial(moe_fwd, top_k=2, cf=1.0)),
        ("moe-cf1.25-k1", functools.partial(moe_fwd, top_k=1, cf=1.25)),
        ("moe-machinery", functools.partial(moe_machinery, top_k=2, cf=1.25)),
        ("moe-bf16comb",
         functools.partial(moe_fwd_bf16comb, top_k=2, cf=1.25)),
    ]
    for name, f in variants:
        def step_fwd(c, mp_):
            return f(mp_, c).astype(c.dtype)

        def step_bwd(c, mp_):
            g = jax.grad(lambda w, xx: jnp.sum(
                f(w, xx).astype(jnp.float32) ** 2), argnums=(0, 1))
            dw, dx = g(mp_, c)
            leaves = jax.tree_util.tree_leaves(dw)
            bump = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            return (c + dx * 0 + bump.astype(c.dtype) * 1e-30).astype(c.dtype)

        row = {"variant": name}
        for kind, stepf, lo, hi in [("fwd", step_fwd, 32, 160),
                                    ("fwdbwd", step_bwd, 16, 80)]:
            margs = []
            for _ in range(2):
                t_lo = chain_total(stepf, lo, x, mp)
                t_hi = chain_total(stepf, hi, x, mp)
                margs.append((t_hi - t_lo) / (hi - lo) * 1e3)
            row[kind + "_ms"] = round(min(margs), 4)
        print(json.dumps(row), flush=True)


if __name__ == "__main__" and "--sweep" not in sys.argv:
    main()


def moe_fwd_c(mp, x, *, top_k, C, reshape_gemm=False):
    """moe_mlp_fwd with the slot count C forced directly (alignment
    probe), optionally reshaping [E, B, C, D] -> [E, B*C, D] so the
    expert matmuls are unambiguous single GEMMs per expert."""
    K = top_k
    logits = jnp.einsum("bld,de->ble", x.astype(jnp.float32), mp["router"])
    probs = jax.nn.softmax(logits, axis=-1)
    remaining, gates, masks = probs, [], []
    for _ in range(K):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, E, dtype=jnp.float32)
        remaining = remaining * (1.0 - mask)
        gates.append((probs * mask).sum(-1))
        masks.append(mask)
    claims = jnp.stack(masks, axis=2).reshape(B, L * K, E)
    pos = jnp.cumsum(claims, axis=1) - claims
    keep_flat = claims * (pos < C)
    slot_idx = (pos * keep_flat).sum(-1).astype(jnp.int32)
    slot_flat = jax.nn.one_hot(slot_idx, C, dtype=jnp.float32)
    keep = keep_flat.reshape(B, L, K, E)
    slot = slot_flat.reshape(B, L, K, C)
    kept_gate = [g * keep[:, :, k].sum(-1) for k, g in enumerate(gates)]
    denom = jnp.maximum(sum(kept_gate), 1e-9)
    combine = jnp.zeros((B, L, E, C), jnp.float32)
    for k, g in enumerate(gates):
        w = (g / denom)[..., None] * keep[:, :, k]
        combine = combine + w[..., None] * slot[:, :, k][:, :, None, :]
    dispatch = (combine > 0).astype(x.dtype)
    xin = jnp.einsum("blec,bld->ebcd", dispatch, x.astype(jnp.bfloat16))
    if reshape_gemm:
        xin2 = xin.reshape(E, B * C, D)
        h = jnp.einsum("exd,edm->exm", xin2, mp["wi"].astype(jnp.bfloat16))
        h = nn.gelu(h, approximate=True)
        out = jnp.einsum("exm,emd->exd", h, mp["wo"].astype(jnp.bfloat16))
        out = out.reshape(E, B, C, D)
    else:
        h = jnp.einsum("ebcd,edm->ebcm", xin, mp["wi"].astype(jnp.bfloat16))
        h = nn.gelu(h, approximate=True)
        out = jnp.einsum("ebcm,emd->ebcd", h, mp["wo"].astype(jnp.bfloat16))
    return jnp.einsum("blec,ebcd->bld",
                      combine.astype(jnp.bfloat16), out).astype(x.dtype)


def main_sweep():
    mp = make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, D), jnp.bfloat16)
    variants = []
    for C in (32, 40, 48, 64):
        variants.append((f"moe-K2-C{C}",
                         functools.partial(moe_fwd_c, top_k=2, C=C)))
    variants.append(("moe-K2-C40-gemm",
                     functools.partial(moe_fwd_c, top_k=2, C=40,
                                       reshape_gemm=True)))
    for name, f in variants:
        def step_fwd(c, mp_):
            return f(mp_, c).astype(c.dtype)

        def step_bwd(c, mp_):
            g = jax.grad(lambda w, xx: jnp.sum(
                f(w, xx).astype(jnp.float32) ** 2), argnums=(0, 1))
            dw, dx = g(mp_, c)
            leaves = jax.tree_util.tree_leaves(dw)
            bump = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            return (c + dx * 0 + bump.astype(c.dtype) * 1e-30).astype(c.dtype)

        row = {"variant": name}
        for kind, stepf, lo, hi in [("fwd", step_fwd, 32, 160),
                                    ("fwdbwd", step_bwd, 16, 80)]:
            margs = []
            for _ in range(2):
                t_lo = chain_total(stepf, lo, x, mp)
                t_hi = chain_total(stepf, hi, x, mp)
                margs.append((t_hi - t_lo) / (hi - lo) * 1e3)
            row[kind + "_ms"] = round(min(margs), 4)
        print(json.dumps(row), flush=True)


if __name__ == "__main__" and "--sweep" in sys.argv:
    main_sweep()
