"""Direct A/B timing of flash fwd / fwd+bwd, per-call dispatch timing with
many repeats (median reported) — sanity harness for kernel changes."""
import json
import statistics
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from distributed_pipeline_tpu.ops.flash_attention import flash_attention


def med_time(fn, *args, n=20):
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return statistics.median(ts)


def main():
    bq = int(sys.argv[1]) if len(sys.argv) > 1 else 1024
    bk = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    for (B, H, L, Dh) in [(2, 12, 4096, 64), (2, 12, 8192, 64)]:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (B, H, L, Dh), jnp.bfloat16)
        k = jax.random.normal(kk, (B, H, L, Dh), jnp.bfloat16)
        v = jax.random.normal(kv, (B, H, L, Dh), jnp.bfloat16)

        fwd = jax.jit(lambda a, b, c: flash_attention(a, b, c, None, True,
                                                      bq, bk))

        def loss(a, b, c):
            return jnp.sum(flash_attention(a, b, c, None, True, bq, bk)
                           .astype(jnp.float32) ** 2)
        gr = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

        print(json.dumps({
            "shape": f"B{B}xH{H}xL{L}xD{Dh}", "block": [bq, bk],
            "fwd_ms": med_time(fwd, q, k, v) * 1e3,
            "fwdbwd_ms": med_time(gr, q, k, v) * 1e3,
        }), flush=True)


if __name__ == "__main__":
    main()
