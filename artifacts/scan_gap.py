"""Decompose the scan_layers MFU gap (r4 bench: 51.1% scan vs 76.5%
unrolled on diffuseq-base seq128). Times a 12-layer stack fwd+bwd at the
bench shape under: python-unrolled layers, lax.scan at several unroll
factors, and scan with the f32->bf16 weight cast hoisted out of the loop.

Long-chain differenced timing (see flash_sweep.py) on the real chip.
"""
import functools
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from distributed_pipeline_tpu.models.pipeline import block_fwd

NL, D, H, B, L = 12, 768, 12, 64, 128


def drain(out):
    float(jax.device_get(jnp.sum(jax.tree_util.tree_leaves(out)[0])
                         .astype(jnp.float32)))


def chain_total(step, reps, *args):
    @jax.jit
    def chain(x, lp):
        def body(_, c):
            return step(c, lp)
        return jax.lax.fori_loop(0, reps, body, x)
    drain(chain(*args))
    t0 = time.perf_counter()
    drain(chain(*args))
    return time.perf_counter() - t0


def make_params(key):
    ks = jax.random.split(key, 8)
    init = lambda k, *s: jax.random.normal(k, s, jnp.float32) * 0.02
    return {
        "ln1_scale": jnp.ones((NL, D)), "ln1_bias": jnp.zeros((NL, D)),
        "qkv": init(ks[0], NL, D, 3, H, D // H),
        "out": init(ks[1], NL, H, D // H, D),
        "ln2_scale": jnp.ones((NL, D)), "ln2_bias": jnp.zeros((NL, D)),
        "wi": init(ks[2], NL, D, 4 * D), "wo": init(ks[3], NL, 4 * D, D),
    }


def fwd_stack_scan(lp, x, unroll):
    def layer(h, one):
        return block_fwd(one, h, None, num_heads=H, dtype=jnp.bfloat16,
                         causal=False, attention_impl="xla"), None
    out, _ = jax.lax.scan(layer, x, lp, unroll=unroll)
    return out


def fwd_stack_unrolled(lp, x):
    for i in range(NL):
        one = jax.tree_util.tree_map(lambda a: a[i], lp)
        x = block_fwd(one, x, None, num_heads=H, dtype=jnp.bfloat16,
                      causal=False, attention_impl="xla")
    return x


def main():
    lp = make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, D), jnp.bfloat16)

    def variants():
        yield "unrolled", lambda lp_, x_: fwd_stack_unrolled(lp_, x_)
        for u in (1, 2, 4, 12):
            yield f"scan-u{u}", functools.partial(
                lambda lp_, x_, u_: fwd_stack_scan(lp_, x_, u_), u_=u)
        # hoist the f32->bf16 weight cast out of the scanned body
        def precast(lp_, x_):
            lpb = jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16), lp_)
            return fwd_stack_scan(lpb, x_, 1)
        yield "scan-u1-precast", precast

    for name, f in variants():
        def step_fwd(c, lp_):
            return f(lp_, c)

        def step_bwd(c, lp_):
            g = jax.grad(lambda w, xx: jnp.sum(
                f(w, xx).astype(jnp.float32) ** 2), argnums=(0, 1))
            dw, dx = g(lp_, c)
            leaves = jax.tree_util.tree_leaves(dw)
            bump = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            return (c + dx * 0 + bump.astype(c.dtype) * 1e-30).astype(c.dtype)

        row = {"variant": name}
        for kind, stepf, lo, hi in [("fwd", step_fwd, 16, 80),
                                    ("fwdbwd", step_bwd, 8, 40)]:
            margs = []
            for _ in range(2):
                t_lo = chain_total(stepf, lo, x, lp)
                t_hi = chain_total(stepf, hi, x, lp)
                margs.append((t_hi - t_lo) / (hi - lo) * 1e3)
            row[kind + "_ms"] = round(min(margs), 3)
        print(json.dumps(row), flush=True)


if __name__ == "__main__" and "--policies" not in sys.argv:
    main()


def fwd_stack_scan_policy(lp, x, policy):
    def layer(h, one):
        return block_fwd(one, h, None, num_heads=H, dtype=jnp.bfloat16,
                         causal=False, attention_impl="xla"), None
    layer = jax.checkpoint(layer, policy=policy, prevent_cse=False)
    out, _ = jax.lax.scan(layer, x, lp)
    return out


def main_policies():
    lp = make_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (B, L, D), jnp.bfloat16)
    cp = jax.checkpoint_policies
    for name, pol in [
        ("remat-full", None),
        ("dots-no-batch", cp.dots_with_no_batch_dims_saveable),
        ("dots", cp.dots_saveable),
    ]:
        f = functools.partial(fwd_stack_scan_policy, policy=pol)

        def step_bwd(c, lp_):
            g = jax.grad(lambda w, xx: jnp.sum(
                f(w, xx).astype(jnp.float32) ** 2), argnums=(0, 1))
            dw, dx = g(lp_, c)
            leaves = jax.tree_util.tree_leaves(dw)
            bump = sum(jnp.sum(l).astype(jnp.float32) for l in leaves)
            return (c + dx * 0 + bump.astype(c.dtype) * 1e-30).astype(c.dtype)

        margs = []
        for _ in range(2):
            t_lo = chain_total(step_bwd, 8, x, lp)
            t_hi = chain_total(step_bwd, 40, x, lp)
            margs.append((t_hi - t_lo) / 32 * 1e3)
        print(json.dumps({"variant": f"scan-u1-{name}",
                          "fwdbwd_ms": round(min(margs), 3)}), flush=True)


if __name__ == "__main__" and "--policies" in sys.argv:
    main_policies()
