import json, sys, time, functools
sys.path.insert(0, "/root/repo")
import jax, jax.numpy as jnp
from distributed_pipeline_tpu.ops.flash_attention import flash_attention

def drain(out):
    float(jax.device_get(jnp.sum(out[0] if isinstance(out, tuple) else out).astype(jnp.float32)))

def chain_total(fn_body, reps, *args):
    @jax.jit
    def chain(q, k, v):
        return jax.lax.fori_loop(0, reps, lambda _, c: fn_body(c, k, v), q)
    drain(chain(*args))
    t0 = time.perf_counter(); drain(chain(*args)); return time.perf_counter() - t0

bq, bk = (int(sys.argv[1]), int(sys.argv[2])) if len(sys.argv) > 2 else (1024, 1024)
for (B, H, L, Dh) in [(2, 12, 4096, 64), (2, 12, 8192, 64)]:
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, H, L, Dh), jnp.bfloat16)
    k = jax.random.normal(kk, (B, H, L, Dh), jnp.bfloat16)
    v = jax.random.normal(kv, (B, H, L, Dh), jnp.bfloat16)
    import os
    mask = (jnp.ones((B, L), jnp.int32) if os.environ.get("WITH_MASK")
            else None)
    fwd_body = lambda c, kk_, vv_: flash_attention(c, kk_, vv_, mask, True, bq, bk)
    g = jax.grad(lambda a,b,c_: jnp.sum(flash_attention(a,b,c_,mask,True,bq,bk).astype(jnp.float32)**2), argnums=(0,1,2))
    def bwd_body(c, kk_, vv_):
        dq, dk, dv = g(c, kk_, vv_)
        return (c + 1e-30*dq + 1e-30*dk + 1e-30*dv).astype(c.dtype)
    # chain lengths long enough that the ~100ms (noisy) tunnel overhead
    # is <5% of the differenced signal; min-of-2 marginals
    for name, body, lo, hi in [("fwd", fwd_body, 64, 320),
                               ("fwdbwd", bwd_body, 16, 80)]:
        margs = []
        for _ in range(2):
            t_lo = chain_total(body, lo, q, k, v)
            t_hi = chain_total(body, hi, q, k, v)
            margs.append((t_hi - t_lo) / (hi - lo) * 1e3)
        print(json.dumps({"shape": f"L{L}", "block": [bq, bk], "kind": name,
                          "per_call_ms": round(min(margs), 3),
                          "all": [round(m, 3) for m in margs]}), flush=True)
