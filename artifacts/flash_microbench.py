"""Flash-kernel microbench on the real chip (dispatch-amortized chained
timing — the axon tunnel costs ~2.7ms/dispatch, so each timed unit is a
jitted chain of REPS dependent kernel calls).

Usage: python artifacts/flash_microbench.py [fwd|bwd|both] [block_q block_k]
Writes one JSON line per shape to stdout.
"""
import json
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp

from distributed_pipeline_tpu.ops.flash_attention import flash_attention

import os

REPS = int(os.environ.get("REPS", "8"))


def _drain(out):
    # device_get forces full completion through the tunnel —
    # block_until_ready under-blocks on axon
    float(jax.device_get(jnp.sum(out[0] if isinstance(out, tuple) else out)
                         .astype(jnp.float32)))


def timeit(fn, *args):
    fn = jax.jit(fn)
    _drain(fn(*args))  # compile + full drain
    t0 = time.perf_counter()
    _drain(fn(*args))
    t1 = time.perf_counter()
    return (t1 - t0) / REPS


def main():
    mode = sys.argv[1] if len(sys.argv) > 1 else "both"
    bq = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
    bk = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
    for (B, H, L, Dh) in [(2, 12, 4096, 64), (2, 12, 8192, 64)]:
        kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
        q = jax.random.normal(kq, (B, H, L, Dh), jnp.bfloat16)
        k = jax.random.normal(kk, (B, H, L, Dh), jnp.bfloat16)
        v = jax.random.normal(kv, (B, H, L, Dh), jnp.bfloat16)

        def fwd_chain(q, k, v):
            def body(_, c):
                return flash_attention(c, k, v, None, True, bq, bk)
            return jax.lax.fori_loop(0, REPS, body, q)

        def bwd_chain(q, k, v):
            g = jax.grad(
                lambda q_, k_, v_: jnp.sum(
                    flash_attention(q_, k_, v_, None, True, bq, bk)
                    .astype(jnp.float32) ** 2),
                argnums=(0, 1, 2))

            def body(_, c):
                dq, dk, dv = g(c, k, v)
                return (c + 0.0 * dq + 0.0 * dk + 0.0 * dv).astype(c.dtype)
            return jax.lax.fori_loop(0, REPS, body, q)

        row = {"shape": f"B{B}xH{H}xL{L}xD{Dh}", "block": [bq, bk]}
        if mode in ("fwd", "both"):
            row["fwd_ms"] = timeit(fwd_chain, q, k, v) * 1e3
        if mode in ("bwd", "both"):
            row["fwdbwd_ms"] = timeit(bwd_chain, q, k, v) * 1e3
        print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
