"""Benchmark: training throughput on the available hardware, per BASELINE.md
config shape — as a STREAMING, BUDGET-AWARE harness.

Output contract (the driver parses stdout, humans watch stderr):

* stdout carries ONE machine-readable JSON line, printed at the end of every
  run — including budget-truncated ones:
    {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...,
     "configs": [...per-leg results, with {"name": ..., "skipped": "budget"}
     markers for legs the wall-clock budget dropped...]}
* every completed leg is ALSO (a) appended immediately to a JSONL artifact
  (``BENCH_ARTIFACT``, default ``bench_legs.jsonl``) and (b) echoed to stderr
  as it finishes — so a timeout can no longer destroy the whole run's signal
  (the r5 failure mode: rc=124 after 12 legs of work, zero numbers captured).

Budget: ``BENCH_BUDGET_S`` (seconds, default 600 — sized to sit inside the
driver's timeout). The headline leg always runs; before each later leg the
elapsed wall clock is checked and remaining legs are skipped with explicit
markers once the budget is spent. Legs run headline-first so a truncated run
always contains the north star.

Compile cost is first-class: a persistent XLA compilation cache
(``BENCH_CACHE_DIR``, default ``model_checkpoints/bench/compile_cache``,
persistent across rounds) makes repeat runs near-compile-free, and every
train leg reports its compile-vs-steady-state split (``compile_s``,
``first_step_s`` vs the steady timed window).

The headline config is BASELINE.md's north star (DiffuSeq-base, seq_len=128,
bf16) WITH the reference's default microbatch-64 gradient accumulation (ref
config/train.py:11-12 — also the measured v5e optimum); the ``configs`` list
covers the other single-chip-benchable BASELINE shapes plus the
exceeds-feature legs (MoE, scan_layers, long-context flash, KV-cache decode).
The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
reports achieved MFU / the 40% MFU target from /root/repo/BASELINE.json.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time


def main() -> None:
    t_bench0 = time.perf_counter()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "600"))
    artifact_path = os.environ.get("BENCH_ARTIFACT", "bench_legs.jsonl")

    import jax

    from distributed_pipeline_tpu.utils import logger
    # stdout is the ONE machine-readable JSON line: silence the logger's
    # sinks (the default logger would print "Logging to ..." on first use).
    logger.configure(format_strs=[])

    from distributed_pipeline_tpu.data import load_data_from_args
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.utils.perf import (
        enable_persistent_compilation_cache,
        mfu,
        transformer_train_flops_per_token,
    )
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    # Persistent compilation cache, stable across bench invocations AND
    # rounds: leg k of run n+1 reuses leg k of run n's XLA compile.
    cache_dir = enable_persistent_compilation_cache(
        os.environ.get("BENCH_CACHE_DIR", "auto"),
        run_dir="model_checkpoints/bench")
    if cache_dir:
        print(f"# compilation cache: {cache_dir}", file=sys.stderr,
              flush=True)

    on_tpu = jax.default_backend() == "tpu"
    dtype = "bfloat16" if on_tpu else "float32"
    steps = 30 if on_tpu else 3

    def measure(name: str, *, family: str, size: str, seq_len: int,
                batch, microbatch: int = 0, remat: bool = False,
                vocab: int = 8192, attention_impl: str = "auto",
                moe_experts: int = 0, moe_top_k: int = 2,
                moe_capacity_factor: float = 1.25,
                scan_layers: bool = False):
        """tokens/sec for one config; the first step is timed separately
        (compile + dispatch) from the steady-state window. ``batch`` is PER
        HOST (reference trainer.py:89 semantics: global = batch x hosts); a
        tuple tries sizes left-to-right and falls back on HBM OOM (the
        driver runs this unattended — a too-ambitious batch must degrade,
        not abort the whole bench)."""
        if isinstance(batch, tuple):
            for i, b in enumerate(batch):
                try:
                    return measure(name, family=family, size=size,
                                   seq_len=seq_len, batch=b,
                                   microbatch=microbatch, remat=remat,
                                   vocab=vocab, attention_impl=attention_impl,
                                   moe_experts=moe_experts,
                                   moe_top_k=moe_top_k,
                                   moe_capacity_factor=moe_capacity_factor,
                                   scan_layers=scan_layers)
                except Exception as e:
                    if i == len(batch) - 1:
                        raise
                    # stderr: stdout is the ONE machine-readable JSON line
                    print(f"# {name}: batch {b} failed ({type(e).__name__}); "
                          f"retrying with {batch[i + 1]}", file=sys.stderr,
                          flush=True)
        # Off-TPU (CPU smoke): shrink the model so every config still
        # EXERCISES its code path (remat, grad-accum, families) in seconds;
        # real preset sizes only matter on the hardware being measured.
        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family=family, model_size=size, seq_len=seq_len,
            dtype=dtype, remat=remat, attention_impl=attention_impl,
            moe_experts=moe_experts, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor,
            scan_layers=scan_layers, **dims)
        dataset = "synthetic-lm" if family == "gpt2" else "synthetic-seq2seq"
        data = load_data_from_args("train", batch_size=batch, dataset=dataset,
                                   seq_len=seq_len,
                                   vocab_size=dims["vocab_size"], seed=0,
                                   num_loader_proc=2)
        # sanitize=True: the runtime half of graftlint — every leg row
        # carries the OBSERVED XLA compile count, so a recompile
        # regression (e.g. an unpinned sharding re-triggering step-2
        # compiles, the r6 bug class) shows up in BENCH artifacts as
        # recompile_count growth instead of a silent throughput dip.
        loop = TrainLoop(model=wl, data=data, batch_size=batch,
                         microbatch=microbatch or batch, lr=1e-4,
                         ema_rate="0.9999", learning_steps=0,
                         log_interval=10 ** 9, save_interval=10 ** 9,
                         mesh=make_mesh(dp=-1), checkpoint_dir="", seed=0,
                         sanitize=True)
        # First step paid separately: with the AOT step (utils/trainer.py)
        # its wall time is compile + dispatch + one step, and
        # loop.compile_time_s isolates the lower()/compile() share — the
        # number the persistent cache collapses on warm runs.
        # try/finally: a leg that dies mid-measure (the HBM-OOM retry path
        # and the per-leg error rows both swallow exceptions) must still
        # detach its monitor — otherwise every failed attempt leaves one
        # more handler on the 'jax' logger and jax_log_compiles stuck on.
        # (A TrainLoop that dies during CONSTRUCTION detaches its own
        # monitor — see TrainLoop.__init__ — so the retry loop above is
        # covered too.)
        try:
            t0 = time.perf_counter()
            m = loop.run_step(next(loop.data))
            float(jax.device_get(m["loss"]))
            first_step_s = time.perf_counter() - t0
            # Warmup: fill the loader prefetch queues + let dispatch
            # pipeline to depth — a cold 1-step warmup undermeasures steady
            # state by ~10% (62.3% -> 68.8% MFU on the v5e headline).
            for _ in range(7 if on_tpu else 0):
                m = loop.run_step(next(loop.data))
            # device_get, not block_until_ready: the latter can UNDER-block
            # through a remote-accelerator tunnel (returns before the queue
            # drains), inflating throughput by whatever was still in flight.
            float(jax.device_get(m["loss"]))
            t0 = time.perf_counter()
            for _ in range(steps):
                m = loop.run_step(next(loop.data))
            float(jax.device_get(m["loss"]))
            dt = time.perf_counter() - t0
        finally:
            recompiles = loop.stop_sanitizer()
        tps = steps * batch * seq_len * jax.process_count() / dt
        # MFU against ACTIVE params: a top-k routed MoE block only runs
        # top_k of its moe_experts expert MLPs per token, so counting every
        # expert's weights would overstate the model flops. Inactive mass
        # is derived from the actual expert weight shapes (leading dim ==
        # moe_experts under a "moe" module) so it tracks models/moe.py by
        # construction.
        n_active = loop.n_params
        if moe_experts > moe_top_k:
            import numpy as np
            from jax.tree_util import tree_flatten_with_path
            leaves, _ = tree_flatten_with_path(loop.state.params)
            # expert dim position differs by layout: named blocks stack
            # experts on dim 0 ([experts, ...]); MoEScanBlocks prepends a
            # scan-group dim ([groups, experts, ...]) — accept either.
            expert_params = sum(
                int(np.prod(leaf.shape))
                for path, leaf in leaves
                if any("moe" in str(getattr(k, "key", k)) for k in path)
                and leaf.ndim >= 2
                and (leaf.shape[0] == moe_experts
                     or (leaf.ndim >= 3 and leaf.shape[1] == moe_experts)))
            n_active -= round(expert_params
                              * (moe_experts - moe_top_k) / moe_experts)
        fpt = transformer_train_flops_per_token(
            n_active, wl.num_layers, wl.hidden_size, seq_len)
        return {
            "name": name,
            "tokens_per_sec_per_chip": round(tps / jax.device_count(), 1),
            "mfu": round(mfu(tps, fpt), 4),
            "n_params": loop.n_params,
            "batch": batch, "microbatch": microbatch or batch,
            "seq_len": seq_len, "remat": remat,
            "compile_s": round(loop.compile_time_s or 0.0, 3),
            "first_step_s": round(first_step_s, 3),
            "time_to_first_step_s": round(loop.time_to_first_step_s or 0.0,
                                          3),
            # total XLA compiles for the WHOLE leg (init + train step +
            # steady window): steady-state growth here is a regression
            # even when tokens/sec still looks plausible
            "recompile_count": recompiles,
        }

    def measure_decode(name: str, *, gen_tokens: int, batch: int,
                       seq_len: int, vocab: int = 8192):
        """KV-cache generation throughput (tokens/sec DECODED, not
        trained): gpt2-base greedy-continues a batch of prompts by
        ``gen_tokens`` single-position cached steps (models/sampling.py
        gpt2_decode prefill + per-token path). Decode is latency-bound —
        each step is one [B, 1, D] forward against the cache — so the
        right scale is tokens/s, not MFU."""
        import jax.numpy as jnp
        import numpy as np

        from distributed_pipeline_tpu.models.sampling import gpt2_decode

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        prompt_len = seq_len - gen_tokens
        ids = jnp.asarray(
            np.random.default_rng(0).integers(4, dims["vocab_size"],
                                              (batch, seq_len), np.int32))
        run = jax.jit(lambda p, i: gpt2_decode(wl, p, i, prompt_len))
        t0 = time.perf_counter()
        out = run(params, ids)  # compile
        float(jax.device_get(out.sum().astype(jnp.float32)))  # full drain
        compile_s = time.perf_counter() - t0
        reps = 3 if on_tpu else 1
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run(params, ids)
        float(jax.device_get(out.sum().astype(jnp.float32)))
        dt = time.perf_counter() - t0
        # plain jit, no mesh: the decode runs on ONE device, so tps IS the
        # per-chip number — dividing by device_count would understate it
        # on multi-chip hosts
        tps = reps * batch * gen_tokens / dt
        return {
            "name": name,
            "decode_tokens_per_sec_per_chip": round(tps, 1),
            "batch": batch, "gen_tokens": gen_tokens, "seq_len": seq_len,
            "prompt_len": prompt_len,
            "compile_s": round(compile_s, 3),
        }

    # Per-chip batch sizes are the measured MFU sweet spots on v5e (base:
    # 64/128/256/512 sweep in r2; large/gpt2 sized to fit one chip's HBM
    # with the single-EMA bench loop); tiny on CPU so smoke runs finish.
    bsz = (lambda b: b if on_tpu else 4)
    # Legs are LAZY (name, thunk) pairs so the budget guard can drop a leg
    # without paying its compile, ordered headline-first so a truncated run
    # always contains the north star.
    legs = [
        # Headline: BASELINE config 2/3 shape with the reference's DEFAULT
        # microbatch of 64 (ref config/train.py:11-12) — which the sweep
        # (16/32/64/128 at batch 256) also measures as the v5e throughput
        # optimum (76% MFU vs 68% unaccumulated: the scan's smaller
        # working set schedules better).
        ("diffuseq-base-seq128", functools.partial(
            measure, "diffuseq-base-seq128", family="diffuseq", size="base",
            seq_len=128, batch=bsz(256), microbatch=bsz(256) // 4 or 1)),
        # no-accumulation variant (pure config-2 semantics)
        ("diffuseq-base-seq128-noaccum", functools.partial(
            measure, "diffuseq-base-seq128-noaccum", family="diffuseq",
            size="base", seq_len=128, batch=bsz(256))),
        # config 3 shape: large model, long sequence, +/- remat. Small
        # microbatches are the big lever at this scale (46% MFU at
        # batch=microbatch=32 -> 69.7% at batch 128/microbatch 4: the tiny
        # per-chunk working set keeps everything near the MXU while the
        # scan amortizes the optimizer/EMA); at these chunk sizes XLA's
        # dense attention beats the flash kernel, which "auto" already
        # picks below 1k context.
        ("diffuseq-large-seq512", functools.partial(
            measure, "diffuseq-large-seq512", family="diffuseq",
            size="large", seq_len=512, batch=(bsz(128), bsz(32), bsz(8)),
            microbatch=bsz(4))),
        ("diffuseq-large-seq512-remat", functools.partial(
            measure, "diffuseq-large-seq512-remat", family="diffuseq",
            size="large", seq_len=512, batch=(bsz(128), bsz(32), bsz(8)),
            microbatch=bsz(8), remat=True)),
        # config 4: the causal-LM path (different xent/attention profile);
        # microbatch 32 is its measured optimum (74.8% vs 66.7% at 128).
        ("gpt2-medium-seq128", functools.partial(
            measure, "gpt2-medium-seq128", family="gpt2", size="medium",
            seq_len=128, batch=(bsz(256), bsz(64), bsz(32)),
            microbatch=bsz(32))),
        # Long context (exceeds the BASELINE shapes): the Pallas flash
        # kernel path — "auto" picks it on TPU from 1k context — at 4k,
        # where the dense [L, L] logits would dominate HBM traffic
        # (measured 1.67x the XLA path at this shape on v5e). The CPU
        # smoke run shrinks the sequence: a 4k dense attention on one CPU
        # core takes minutes and measures nothing.
        # batch/microbatch are the r4 sweep optimum (saturates from b=32;
        # microbatch 2 beats 1 and 4 at both lengths); 1024x1024 kernel
        # blocks + the diagonal-only causal masking lifted this shape
        # 41.5% -> 49.6% MFU (PARITY.md long-context section).
        ("gpt2-base-seq4096-flash", functools.partial(
            measure, "gpt2-base-seq4096-flash", family="gpt2", size="base",
            seq_len=4096 if on_tpu else 256,
            batch=(bsz(64), bsz(16), bsz(4)), microbatch=bsz(2))),
        # Long-context curve extension: 8k context through the same flash
        # path (quadratic attention share doubles vs 4k).
        ("gpt2-base-seq8192-flash", functools.partial(
            measure, "gpt2-base-seq8192-flash", family="gpt2", size="base",
            seq_len=8192 if on_tpu else 256,
            batch=(bsz(32), bsz(8), bsz(2)), microbatch=bsz(2))),
        # MoE: 8 experts top-2 in every 2nd block — measures the one-hot
        # dispatch/combine einsum cost on real hardware (MFU against
        # ACTIVE params: only top_k experts run per token).
        ("diffuseq-base-seq128-moe8", functools.partial(
            measure, "diffuseq-base-seq128-moe8", family="diffuseq",
            size="base", seq_len=128, batch=(bsz(256), bsz(64)),
            microbatch=bsz(256) // 4 or 1, moe_experts=8, moe_top_k=2)),
        # Same MoE at capacity_factor 1.0: zero padding slots (E*C == K*L).
        # artifacts/moe_gap.py decomposes the moe8 MFU gap — at cf 1.25 the
        # expert GEMMs pay ~2x the +25% slot flops (non-power-of-two row
        # tiling), at cf 1.0 they run at dense efficiency; the knob
        # (--moe_capacity_factor) trades overflow drops for throughput.
        ("diffuseq-base-seq128-moe8-cf1", functools.partial(
            measure, "diffuseq-base-seq128-moe8-cf1", family="diffuseq",
            size="base", seq_len=128, batch=(bsz(256), bsz(64)),
            microbatch=bsz(256) // 4 or 1, moe_experts=8, moe_top_k=2,
            moe_capacity_factor=1.0)),
        # scan_layers: the stacked-weights layer scan (one traced block) —
        # quantifies the compile-time-vs-MFU tradeoff PARITY.md documents,
        # in the driver signal.
        ("diffuseq-base-seq128-scan", functools.partial(
            measure, "diffuseq-base-seq128-scan", family="diffuseq",
            size="base", seq_len=128, batch=bsz(256),
            microbatch=bsz(256) // 4 or 1, scan_layers=True)),
        # KV-cache decode throughput (generation, not training) at two
        # batch sizes — the pair anchors the batch-scaling curve (decode
        # is latency-bound per step, so tokens/s should scale near-
        # linearly with batch until the weight-streaming bandwidth wall).
        ("gpt2-base-decode128", functools.partial(
            measure_decode, "gpt2-base-decode128",
            gen_tokens=128 if on_tpu else 8,
            batch=bsz(64), seq_len=1024 if on_tpu else 64)),
        ("gpt2-base-decode128-b8", functools.partial(
            measure_decode, "gpt2-base-decode128-b8",
            gen_tokens=128 if on_tpu else 8,
            batch=8 if on_tpu else 2,
            seq_len=1024 if on_tpu else 64)),
    ]

    only = os.environ.get("BENCH_ONLY", "")
    if only:  # iteration filter: BENCH_ONLY=<substring>
        legs = [(n, f) for n, f in legs if only in n]

    # Fresh artifact per run (a crash mid-run leaves the completed prefix).
    if artifact_path:
        open(artifact_path, "w").close()

    configs = []

    def emit(row: dict) -> None:
        """Record one leg NOW: final-JSON list + JSONL artifact + stderr.
        A later timeout/crash can only lose legs that never finished."""
        configs.append(row)
        if artifact_path:
            with open(artifact_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        print(f"# leg {json.dumps(row)} [t+"
              f"{time.perf_counter() - t_bench0:.0f}s]", file=sys.stderr,
              flush=True)

    for i, (name, thunk) in enumerate(legs):
        elapsed = time.perf_counter() - t_bench0
        # The HEADLINE leg (first in the list) is exempt: a bench run that
        # reports nothing is strictly worse than one that overruns a little,
        # and the final JSON's `value` is this leg.
        if i > 0 and elapsed > budget_s:
            emit({"name": name, "skipped": "budget"})
            continue
        try:
            emit(thunk())
        except Exception as e:
            # One leg must not sink the others (or the final JSON line).
            emit({"name": name,
                  "error": f"{type(e).__name__}: {e}"[:500]})

    # The headline contract holds only for a FULL leg list (legs[0] is the
    # DiffuSeq north star). Under BENCH_ONLY (iteration mode) the first
    # surviving train config — if any — is reported under its own name,
    # never as the north star. In a full run the headline value must come
    # from the headline LEG specifically: if that leg errored, report null
    # (its error row stays in configs) rather than silently promoting the
    # next leg's numbers under the north-star label.
    if only:
        head = next((c for c in configs if "mfu" in c), None)
    else:
        head = configs[0] if configs and "mfu" in configs[0] else None
    if only and head is not None:
        metric = (f"tokens/sec/chip ({head['name']} [BENCH_ONLY={only}], "
                  f"{jax.devices()[0].device_kind})")
    else:
        metric = ("tokens/sec/chip (DiffuSeq-base seq128 train, "
                  f"{jax.devices()[0].device_kind})")
    print(json.dumps({
        "metric": metric,
        "value": head["tokens_per_sec_per_chip"] if head else None,
        "unit": "tokens/s/chip",
        "vs_baseline": round(head["mfu"] / 0.40, 4) if head else None,
        "mfu": head["mfu"] if head else None,
        "n_params": head["n_params"] if head else None,
        "n_devices": jax.device_count(),
        "budget_s": budget_s,
        "elapsed_s": round(time.perf_counter() - t_bench0, 1),
        "compilation_cache": cache_dir,
        "configs": configs,
    }))


if __name__ == "__main__":
    main()
