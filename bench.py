"""Benchmark: DiffuSeq-base training throughput on the available hardware.

Prints ONE JSON line:
  {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...}

The headline config is BASELINE.md's north star (DiffuSeq-base, seq_len=128,
bf16). The reference publishes no absolute numbers (BASELINE.md), so
``vs_baseline`` reports achieved MFU / the 40% MFU target from
/root/repo/BASELINE.json.
"""

from __future__ import annotations

import json
import time


def main() -> None:
    import jax
    import jax.numpy as jnp

    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.utils.perf import (
        mfu,
        transformer_train_flops_per_token,
    )
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    on_tpu = jax.default_backend() == "tpu"
    seq_len = 128
    # Per-chip batch 256 is the measured MFU sweet spot at base scale
    # (64/128/256/512 sweep on v5e); tiny on CPU so smoke runs finish fast.
    # batch is PER HOST (trainer.py:89 semantics), so scale by the host's
    # local chips, not the global device count.
    batch = 256 * jax.local_device_count() if on_tpu else 8
    steps = 30 if on_tpu else 3
    wl = create_model_from_config(
        model_family="diffuseq", model_size="base", vocab_size=8192,
        seq_len=seq_len, dtype="bfloat16" if on_tpu else "float32")

    from distributed_pipeline_tpu.data import load_data_from_args
    data = load_data_from_args("train", batch_size=batch,
                               dataset="synthetic-seq2seq", seq_len=seq_len,
                               vocab_size=8192, seed=0, num_loader_proc=2)

    def measure(microbatch: int):
        """tokens/sec (global: per-host batch x hosts, trainer.py:89) for one
        accumulation config; warmup step compiles, then a timed window."""
        loop = TrainLoop(model=wl, data=data, batch_size=batch,
                         microbatch=microbatch, lr=1e-4, ema_rate="0.9999",
                         learning_steps=0, log_interval=10 ** 9,
                         save_interval=10 ** 9, mesh=make_mesh(dp=-1),
                         checkpoint_dir="", seed=0)
        m = loop.run_step(next(loop.data))
        jax.block_until_ready(m["loss"])
        t0 = time.perf_counter()
        for _ in range(steps):
            m = loop.run_step(next(loop.data))
        jax.block_until_ready(m["loss"])
        dt = time.perf_counter() - t0
        return steps * batch * seq_len * jax.process_count() / dt, loop.n_params

    # headline: no accumulation (BASELINE config 2 shape) ...
    tokens_per_sec, n_params = measure(microbatch=batch)
    # ... plus the grad-accum path (BASELINE config 3: microbatch < batch,
    # lax.scan accumulation inside the jitted step).
    accum_tokens_per_sec, _ = measure(microbatch=max(batch // 4, 1))

    per_chip = tokens_per_sec / jax.device_count()
    fpt = transformer_train_flops_per_token(
        n_params, wl.num_layers, wl.hidden_size, seq_len)
    achieved_mfu = mfu(tokens_per_sec, fpt)
    print(json.dumps({
        "metric": "tokens/sec/chip (DiffuSeq-base seq128 train, "
                  f"{jax.devices()[0].device_kind})",
        "value": round(per_chip, 1),
        "unit": "tokens/s/chip",
        "vs_baseline": round(achieved_mfu / 0.40, 4),
        "mfu": round(achieved_mfu, 4),
        "grad_accum_tokens_per_sec_per_chip": round(
            accum_tokens_per_sec / jax.device_count(), 1),
        "grad_accum_mfu": round(mfu(accum_tokens_per_sec, fpt), 4),
        "n_params": n_params,
        "n_devices": jax.device_count(),
    }))


if __name__ == "__main__":
    main()
