"""Benchmark: training throughput on the available hardware, per BASELINE.md
config shape — as a STREAMING, BUDGET-AWARE harness.

Output contract (the driver parses stdout, humans watch stderr):

* stdout carries ONE machine-readable JSON line, printed at the end of every
  run — including budget-truncated ones:
    {"metric": ..., "value": tokens/sec/chip, "unit": ..., "vs_baseline": ...,
     "configs": [...per-leg results, with {"name": ..., "skipped": "budget"}
     markers for legs the wall-clock budget dropped...]}
* every completed leg is ALSO (a) appended immediately to a JSONL artifact
  (``BENCH_ARTIFACT``, default ``bench_legs.jsonl``) and (b) echoed to stderr
  as it finishes — so a timeout can no longer destroy the whole run's signal
  (the r5 failure mode: rc=124 after 12 legs of work, zero numbers captured).

Budget: ``BENCH_BUDGET_S`` (seconds, default 480 — sized to sit inside the
driver's timeout with headroom). The headline leg always runs; before each
later leg the elapsed wall clock is checked and remaining legs are skipped
with explicit markers once the budget is spent. Legs run headline-first so a
truncated run always contains the north star.

Three layers make ``parsed: null`` impossible (the BENCH_r05 regression —
rc=124 with ZERO rows because the run wedged inside a leg):

1. per-leg HARD CAP: every leg runs under a SIGALRM deadline
   (``BENCH_LEG_BUDGET_S``, default 240, further clamped to the remaining
   budget; the headline leg gets max(80% of the whole budget, 120s) — it is
   exempt from the budget SKIP but not from a wedge cap). A leg that
   overruns becomes an ``error`` row, not a hung process.
2. SIGTERM net: the driver's soft kill is caught, remaining legs are
   marked skipped, and the final JSON still prints.
3. watchdog thread: if the main thread is wedged in native code (where a
   Python signal handler cannot run — a stuck compile or a wedged remote
   chip), a daemon watchdog prints the final JSON from the completed rows
   at budget+60s and exits 3.

Steady-state A/B (ISSUE 5): the headline (prefetch OFF) is immediately
followed by a PAIRED A/B leg at the same config — an OFF loop and an ON
loop (device prefetch + async metrics dispatch,
``BENCH_PREFETCH_DEPTH``/``BENCH_DISPATCH_LAG``, defaults 2/1) both kept
alive while short timed windows interleave between them, order
alternating each round. Sequential legs measure the box as much as the
code (a shared host's steady-state rate drifts enough to flip the delta
sign run to run); interleaving hits both arms with the same drift, and
the ``prefetch-ab-delta`` row reports the position-balanced totals ratio
(ABBA ordering cancels the measured second-window position cost). Every
train row carries ``steps_per_s`` plus the four stall-breakdown gauges
(``data_wait_s``/``h2d_wait_s``/``dispatch_s``/``device_step_s``, mean
seconds per step over the timed window) and the HBM/params footprint
columns (``params_bytes``/``opt_state_bytes``/
``opt_state_bytes_per_replica``/``peak_live_bytes``, ISSUE 9).

ZeRO-1 A/B (ISSUE 9): ``diffuseq-base-seq128-zero1`` runs the same
paired-interleaved protocol between ``--shard_optimizer`` ON and OFF in a
child process with a >= 2-way data axis (run/zero1_ab.py); the
``zero1-ab-delta`` row reports steps/s parity plus the ~dp x per-replica
optimizer-bytes drop.

Auto-tuner leg (ISSUE 13): ``diffuseq-base-seq128-tune`` runs a
screen-only budgeted layout search (rule tables x mesh splits, tune/) on
the forced-host dp=2 CPU mesh and passes only if the tuner reproduces or
beats the hand-tuned table's steps/s within the +-3% band with every
enumerated candidate accounted (completed + pruned + rejected + skipped
== enumerated). Child spawn/env/timeout folding for BOTH child legs is
owned by tune/measure.py.

``BENCH_ONLY`` selects legs by EXACT name, or by glob when it contains a
wildcard (``diffuseq-base-seq128*`` = the old substring behavior).

Compile cost is first-class: a persistent XLA compilation cache
(``BENCH_CACHE_DIR``, default ``model_checkpoints/bench/compile_cache``,
persistent across rounds) makes repeat runs near-compile-free, and every
train leg reports its compile-vs-steady-state split (``compile_s``,
``first_step_s`` vs the steady timed window).

The headline config is BASELINE.md's north star (DiffuSeq-base, seq_len=128,
bf16) WITH the reference's default microbatch-64 gradient accumulation (ref
config/train.py:11-12 — also the measured v5e optimum); the ``configs`` list
covers the other single-chip-benchable BASELINE shapes plus the
exceeds-feature legs (MoE, scan_layers, long-context flash, KV-cache decode).
The reference publishes no absolute numbers (BASELINE.md), so ``vs_baseline``
reports achieved MFU / the 40% MFU target from /root/repo/BASELINE.json.
"""

from __future__ import annotations

import functools
import json
import os
import signal
import sys
import threading
import time


def select_legs(legs, only):
    """``BENCH_ONLY`` leg filter: EXACT name match, or an fnmatch glob
    when the pattern contains a wildcard (``*``/``?``/``[``). The old
    substring filter made ``BENCH_ONLY=diffuseq-base-seq128`` run seven
    legs — chaos and the A/B twins included — when the point of the knob
    is iterating on ONE leg; ``diffuseq-base-seq128*`` now spells the
    old family-wide behavior explicitly."""
    if not only:
        return list(legs)
    import fnmatch

    if any(c in only for c in "*?["):
        return [(n, f) for n, f in legs if fnmatch.fnmatchcase(n, only)]
    return [(n, f) for n, f in legs if n == only]


class LegTimeout(Exception):
    """A leg overran its SIGALRM hard cap."""


class BenchInterrupted(Exception):
    """The driver sent SIGTERM (its soft kill before SIGKILL)."""


def _run_capped(thunk, cap_s: float):
    """Run one leg under a SIGALRM deadline. Raises LegTimeout on overrun
    so the leg becomes an error row instead of a hung process. (A native
    call that never returns to the interpreter can still outlive this —
    the watchdog thread is the terminal backstop for that case.)"""

    def _on_alarm(signum, frame):
        raise LegTimeout(f"leg exceeded its {cap_s:.0f}s hard cap")

    unset = object()
    row = unset
    prev = signal.signal(signal.SIGALRM, _on_alarm)
    signal.setitimer(signal.ITIMER_REAL, max(cap_s, 1.0))
    try:
        try:
            try:
                row = thunk()
            finally:
                # cleared the instant the call ends — success OR error —
                # so a late alarm can neither land in the caller's
                # cleanup nor replace a real exception mid-unwind
                signal.setitimer(signal.ITIMER_REAL, 0.0)
        except LegTimeout:
            if row is not unset:
                # The alarm fired in the gap between the leg completing
                # and the itimer being cleared: the row is fully computed
                # — keep it instead of discarding a finished leg.
                return row
            raise
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, prev)
    return row


def main() -> None:
    t_bench0 = time.perf_counter()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "480"))
    leg_budget_s = float(os.environ.get("BENCH_LEG_BUDGET_S", "240"))
    artifact_path = os.environ.get("BENCH_ARTIFACT", "bench_legs.jsonl")

    import jax

    from distributed_pipeline_tpu.utils import logger
    # stdout is the ONE machine-readable JSON line: silence the logger's
    # sinks (the default logger would print "Logging to ..." on first use).
    logger.configure(format_strs=[])

    from distributed_pipeline_tpu.data import load_data_from_args
    from distributed_pipeline_tpu.models import create_model_from_config
    from distributed_pipeline_tpu.parallel import make_mesh
    from distributed_pipeline_tpu.obs import ledger as ledger_lib
    from distributed_pipeline_tpu.utils.perf import (
        active_param_count,
        enable_persistent_compilation_cache,
        mfu,
        transformer_train_flops_per_token,
    )
    from distributed_pipeline_tpu.utils.trainer import TrainLoop

    # Persistent compilation cache, stable across bench invocations AND
    # rounds: leg k of run n+1 reuses leg k of run n's XLA compile.
    cache_dir = enable_persistent_compilation_cache(
        os.environ.get("BENCH_CACHE_DIR", "auto"),
        run_dir="model_checkpoints/bench")
    if cache_dir:
        print(f"# compilation cache: {cache_dir}", file=sys.stderr,
              flush=True)

    on_tpu = jax.default_backend() == "tpu"
    dtype = "bfloat16" if on_tpu else "float32"
    steps = 30 if on_tpu else 3

    def _train_ledger_columns(loop, *, tps: float, fpt: float,
                              steps_per_s: float, stall: dict) -> dict:
        """The cost-ledger columns for one train row (ISSUE 14): the
        executable's extracted collective/HBM traffic folded with THIS
        leg's measured tokens/s and (MoE-active) flops/token into the
        roofline MFU-gap attribution — so the row's ``mfu`` and its
        ``mfu_gap_*`` terms share one numerator and the sum identity
        (mfu + gaps == 1) holds exactly. The attribution arithmetic has
        one owner (obs/ledger.py; graftlint GL010)."""
        from distributed_pipeline_tpu.utils.perf import device_peak_flops

        tr = loop.ledger_rows().get("train_step") or {}
        att = ledger_lib.roofline_attribution(
            tokens_per_s=tps, flops_per_token=fpt,
            peak_flops=device_peak_flops(),
            n_devices=jax.device_count(), steps_per_s=steps_per_s,
            collective_bytes_per_step=tr.get("collective_bytes_per_step",
                                             0.0),
            bytes_accessed=tr.get("bytes_accessed", 0.0),
            host_stall_s_per_step=(stall["data_wait_s"]
                                   + stall["h2d_wait_s"]
                                   + stall["dispatch_s"]),
            device_kind=getattr(jax.devices()[0], "device_kind", "cpu"),
            padding_waste_frac=tr.get("padding_waste_frac", 0.0))
        cols = ledger_lib.attribution_columns(att)
        for k in ("flops_per_execution", "bytes_accessed"):
            if k in tr:
                cols[k] = tr[k]
        return cols

    def measure(name: str, *, family: str, size: str, seq_len: int,
                batch, microbatch: int = 0, remat: bool = False,
                vocab: int = 8192, attention_impl: str = "auto",
                moe_experts: int = 0, moe_top_k: int = 2,
                moe_capacity_factor: float = 1.25,
                scan_layers: bool = False,
                prefetch_depth: int = 0, dispatch_lag: int = 0,
                steady_steps: int = 0, fused_update: bool = False):
        """tokens/sec for one config; the first step is timed separately
        (compile + dispatch) from the steady-state window. ``batch`` is PER
        HOST (reference trainer.py:89 semantics: global = batch x hosts); a
        tuple tries sizes left-to-right and falls back on HBM OOM (the
        driver runs this unattended — a too-ambitious batch must degrade,
        not abort the whole bench)."""
        if isinstance(batch, tuple):
            for i, b in enumerate(batch):
                try:
                    return measure(name, family=family, size=size,
                                   seq_len=seq_len, batch=b,
                                   microbatch=microbatch, remat=remat,
                                   vocab=vocab, attention_impl=attention_impl,
                                   moe_experts=moe_experts,
                                   moe_top_k=moe_top_k,
                                   moe_capacity_factor=moe_capacity_factor,
                                   scan_layers=scan_layers,
                                   prefetch_depth=prefetch_depth,
                                   dispatch_lag=dispatch_lag,
                                   steady_steps=steady_steps,
                                   fused_update=fused_update)
                except (LegTimeout, BenchInterrupted):
                    # Not an OOM: the per-leg SIGALRM cap / driver SIGTERM
                    # must reach the leg runner, not restart at a smaller
                    # batch with the itimer already consumed.
                    raise
                except Exception as e:
                    if i == len(batch) - 1:
                        raise
                    # stderr: stdout is the ONE machine-readable JSON line
                    print(f"# {name}: batch {b} failed ({type(e).__name__}); "
                          f"retrying with {batch[i + 1]}", file=sys.stderr,
                          flush=True)
        # Off-TPU (CPU smoke): shrink the model so every config still
        # EXERCISES its code path (remat, grad-accum, families) in seconds;
        # real preset sizes only matter on the hardware being measured.
        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family=family, model_size=size, seq_len=seq_len,
            dtype=dtype, remat=remat, attention_impl=attention_impl,
            moe_experts=moe_experts, moe_top_k=moe_top_k,
            moe_capacity_factor=moe_capacity_factor,
            scan_layers=scan_layers, **dims)
        dataset = "synthetic-lm" if family == "gpt2" else "synthetic-seq2seq"
        data = load_data_from_args("train", batch_size=batch, dataset=dataset,
                                   seq_len=seq_len,
                                   vocab_size=dims["vocab_size"], seed=0,
                                   num_loader_proc=2)
        # sanitize=True: the runtime half of graftlint — every leg row
        # carries the OBSERVED XLA compile count, so a recompile
        # regression (e.g. an unpinned sharding re-triggering step-2
        # compiles, the r6 bug class) shows up in BENCH artifacts as
        # recompile_count growth instead of a silent throughput dip.
        # cost_ledger=True: every train row carries the per-program
        # roofline attribution (obs/ledger.py) — the MFU gap explained,
        # not just stated (ISSUE 14).
        loop = TrainLoop(model=wl, data=data, batch_size=batch,
                         microbatch=microbatch or batch, lr=1e-4,
                         ema_rate="0.9999", learning_steps=0,
                         log_interval=10 ** 9, save_interval=10 ** 9,
                         mesh=make_mesh(dp=-1), checkpoint_dir="", seed=0,
                         sanitize=True, prefetch_depth=prefetch_depth,
                         dispatch_lag=dispatch_lag, cost_ledger=True,
                         fused_update=fused_update)
        # First step paid separately: with the AOT step (utils/trainer.py)
        # its wall time is compile + dispatch + one step, and
        # loop.compile_time_s isolates the lower()/compile() share — the
        # number the persistent cache collapses on warm runs.
        # try/finally: a leg that dies mid-measure (the HBM-OOM retry path
        # and the per-leg error rows both swallow exceptions) must still
        # detach its monitor — otherwise every failed attempt leaves one
        # more handler on the 'jax' logger and jax_log_compiles stuck on.
        # (A TrainLoop that dies during CONSTRUCTION detaches its own
        # monitor — see TrainLoop.__init__ — so the retry loop above is
        # covered too.)
        n_steady = steady_steps or steps
        try:
            t0 = time.perf_counter()
            m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            first_step_s = time.perf_counter() - t0
            # Warmup: fill the loader prefetch queues + let dispatch
            # pipeline to depth — a cold 1-step warmup undermeasures steady
            # state by ~10% (62.3% -> 68.8% MFU on the v5e headline).
            for _ in range(7 if on_tpu else 2):
                m = loop.run_step(loop.next_batch())
            # device_get, not block_until_ready: the latter can UNDER-block
            # through a remote-accelerator tunnel (returns before the queue
            # drains), inflating throughput by whatever was still in flight.
            float(jax.device_get(m["loss"]))
            loop.stalls.lap()  # reset the window: gauges cover ONLY the
            # steady timed steps below, not compile/warmup
            t0 = time.perf_counter()
            for _ in range(n_steady):
                m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            dt = time.perf_counter() - t0
            # flush BEFORE lap: the drain emits the last dispatch_lag
            # steps' device_step_s samples into the stall window (same
            # order as measure_prefetch_ab)
            loop.flush_metrics()
            stall = loop.stalls.lap()
        finally:
            recompiles = loop.stop_sanitizer()
        tps = n_steady * batch * seq_len * jax.process_count() / dt
        # MFU against ACTIVE params: perf.active_param_count owns the
        # top-k MoE adjustment (graftlint GL010: FLOPs-side accounting
        # has one owner — this used to be ~20 inline lines here).
        n_active = active_param_count(loop.state.params, loop.n_params,
                                      moe_experts=moe_experts,
                                      moe_top_k=moe_top_k)
        fpt = transformer_train_flops_per_token(
            n_active, wl.num_layers, wl.hidden_size, seq_len)
        row = {
            "name": name,
            "tokens_per_sec_per_chip": round(tps / jax.device_count(), 1),
            "steps_per_s": round(n_steady / dt, 4),
            "mfu": round(mfu(tps, fpt), 4),
            "n_params": loop.n_params,
            "batch": batch, "microbatch": microbatch or batch,
            "seq_len": seq_len, "remat": remat,
            "prefetch_depth": prefetch_depth, "dispatch_lag": dispatch_lag,
            "compile_s": round(loop.compile_time_s or 0.0, 3),
            "first_step_s": round(first_step_s, 3),
            "time_to_first_step_s": round(loop.time_to_first_step_s or 0.0,
                                          3),
            # total XLA compiles for the WHOLE leg (init + train step +
            # steady window): steady-state growth here is a regression
            # even when tokens/sec still looks plausible
            "recompile_count": recompiles,
        }
        # HBM/params footprint (ISSUE 9): logical + per-replica state
        # bytes — opt_state_bytes_per_replica is the ZeRO-1 acceptance
        # column — and the backend's peak live allocation (0 on CPU).
        fp = loop.footprint()
        row.update({k: fp[k] for k in (
            "params_bytes", "opt_state_bytes",
            "opt_state_bytes_per_replica", "peak_live_bytes")})
        # Stall breakdown over the timed window (mean s/step): data_wait_s
        # (blocked on the host iterator), h2d_wait_s (blocked on transfer/
        # placement), dispatch_s (enqueue), device_step_s (trailing
        # dispatch->ready span, observed via the lagged fetch; 0.0 in
        # eager-dispatch legs, which never block on a step to measure it).
        row.update({k: round(v, 6) for k, v in stall.items()})
        # Cost ledger (ISSUE 14): mfu (unrounded — the gap-sum identity
        # must hold to 1e-6) + mfu_gap_host/comms/memory_bound/residual
        # + collective_bytes_per_step + padding_waste_frac, off the leg's
        # own compiled executable and timed window.
        row.update(_train_ledger_columns(loop, tps=tps, fpt=fpt,
                                         steps_per_s=n_steady / dt,
                                         stall=stall))
        if fused_update:
            # Fused-update HBM accounting (ISSUE 18): kernel arm = the
            # exact per-step traffic of the one-pass kernel
            # (ops/fused_update.py update_hbm_bytes — the TPU lowering's
            # bytes by construction; interpreter emulation can't be
            # cost-analyzed faithfully); XLA twin = cost analysis of the
            # staged optax chain this path replaces, compiled standalone
            # on the leg's own state shapes.
            import optax as _optax

            from distributed_pipeline_tpu.ops.fused_update import (
                update_hbm_bytes,
            )
            st = loop.state
            tmap = jax.tree_util.tree_map
            rates = loop.ema_rates
            rate_val = {r: float(r) for r in rates}  # hoisted: trace-free

            def staged(params, grads, opt_state, ema):
                updates, ns = loop.opt.update(grads, opt_state, params)
                p2 = _optax.apply_updates(params, updates)
                e2 = {r: tmap(lambda e, p, _r=rate_val[r]:
                              e * _r + p * (1.0 - _r), ema[r], p2)
                      for r in rates}
                return p2, ns, e2

            abstract = tmap(lambda x: jax.ShapeDtypeStruct(x.shape,
                                                           x.dtype),
                            (st.params, st.params, st.opt_state, st.ema))
            twin = jax.jit(staged).lower(*abstract).compile()
            xla_bytes = ledger_lib.extract_cost(twin).get(
                "bytes_accessed", 0.0)
            kernel_bytes = update_hbm_bytes(
                st.params, n_ema_rates=len(rates),
                dtype_bytes=2 if dtype == "bfloat16" else 4)
            row.update({
                "fused_update": True,
                "update_hbm_bytes_per_step": kernel_bytes,
                "xla_update_bytes_per_step": round(xla_bytes, 1),
                "update_bytes_ratio": round(
                    kernel_bytes / max(xla_bytes, 1e-9), 4),
            })
        return row

    def measure_decode(name: str, *, gen_tokens: int, batch: int,
                       seq_len: int, vocab: int = 8192):
        """KV-cache generation throughput (tokens/sec DECODED, not
        trained): gpt2-base greedy-continues a batch of prompts by
        ``gen_tokens`` single-position cached steps (models/sampling.py
        gpt2_decode prefill + per-token path). Decode is latency-bound —
        each step is one [B, 1, D] forward against the cache — so the
        right scale is tokens/s, not MFU."""
        import jax.numpy as jnp
        import numpy as np

        from distributed_pipeline_tpu.models.sampling import gpt2_decode

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        prompt_len = seq_len - gen_tokens
        ids = jnp.asarray(
            np.random.default_rng(0).integers(4, dims["vocab_size"],
                                              (batch, seq_len), np.int32))
        run = jax.jit(lambda p, i: gpt2_decode(wl, p, i, prompt_len))
        t0 = time.perf_counter()
        out = run(params, ids)  # compile
        float(jax.device_get(out.sum().astype(jnp.float32)))  # full drain
        compile_s = time.perf_counter() - t0
        reps = 3 if on_tpu else 1
        t0 = time.perf_counter()
        for _ in range(reps):
            out = run(params, ids)
        float(jax.device_get(out.sum().astype(jnp.float32)))
        dt = time.perf_counter() - t0
        # plain jit, no mesh: the decode runs on ONE device, so tps IS the
        # per-chip number — dividing by device_count would understate it
        # on multi-chip hosts
        tps = reps * batch * gen_tokens / dt
        return {
            "name": name,
            "decode_tokens_per_sec_per_chip": round(tps, 1),
            # canonical serving-schema column (same value; the serve legs
            # write only this spelling — keep both until consumers migrate)
            "decode_tokens_per_s_per_chip": round(tps, 1),
            "batch": batch, "gen_tokens": gen_tokens, "seq_len": seq_len,
            "prompt_len": prompt_len,
            "compile_s": round(compile_s, 3),
        }

    def measure_serve(name: str, *, slots: int, num_requests: int,
                      gen_tokens: int, prompt_len: int, page_size: int,
                      seq_len: int, prefill_batch: int = 0,
                      decode_span: int = 4, dispatch_lag: int = 2,
                      vocab: int = 8192):
        """Continuous-batching decode service throughput (serving/): N
        requests stream through a DecodeServer whose compiled decode batch
        stays full — prefill/decode as separate AOT executables over the
        paged KV cache. Reported per the serving schema:
        ``decode_tokens_per_s_per_chip`` over the timed (post-warmup)
        window plus ``time_to_first_token_s`` mean and p95 (TTFT includes
        queue wait — the number a user feels). ``recompile_count`` is the
        STEADY-window compile delta: the phase split's contract is that it
        stays 0 (both executables compile exactly once, in warmup)."""
        import numpy as np

        from distributed_pipeline_tpu.serving import DecodeServer

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        # decode_span amortizes host dispatch over several tokens (the
        # token chain stays on device inside one executable); dispatch_lag
        # keeps a couple of dispatches in flight so scheduler bookkeeping
        # overlaps device execution instead of serializing per window
        server = DecodeServer(
            wl, params, decode_slots=slots, page_size=page_size,
            max_prompt_len=prompt_len, max_len=prompt_len + gen_tokens,
            prefill_batch=prefill_batch, decode_span=decode_span,
            dispatch_lag=dispatch_lag, seed=0, sanitize=True)
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            4, dims["vocab_size"], (num_requests, prompt_len)).astype(
                np.int32)
        try:
            # Warmup request: pays the prefill+decode AOT compiles and
            # fills the dispatch pipeline; excluded from the timed window.
            t0 = time.perf_counter()
            server.submit(prompts[0], max_new_tokens=gen_tokens)
            server.drain()
            first_request_s = time.perf_counter() - t0
            compile_s = server.compile_time_s
            recompiles_warm = server.recompile_count
            server.reset_stats()
            t0 = time.perf_counter()
            for p in prompts[1:]:
                server.submit(p, max_new_tokens=gen_tokens)
            server.drain()
            dt = time.perf_counter() - t0
            steady_recompiles = server.recompile_count - recompiles_warm
        finally:
            server.stop_sanitizer()
        ttft = server.ttft.summary()
        # replicated decode state: the service rate IS the per-chip rate
        # (see measure_decode's no-division rationale)
        tps = server.tokens_fetched / dt
        # Cost ledger (ISSUE 14): the decode executable's roofline
        # attribution over the timed window (stats were reset after
        # warmup, so tokens_fetched and wall line up), plus the prefill
        # prompt-padding waste as its own column.
        led = server.cost_ledger(wall_s=dt, n_devices=1)
        ledger_cols = ledger_lib.attribution_columns(
            led.get("serve_decode") or {})
        pre = led.get("serve_prefill") or {}
        if "padding_waste_frac" in pre:
            ledger_cols["prefill_padding_waste_frac"] = \
                pre["padding_waste_frac"]
        return {
            "name": name,
            "decode_tokens_per_s_per_chip": round(tps, 1),
            "time_to_first_token_s": round(ttft["mean"], 4),
            "ttft_p95_s": round(ttft["p95"], 4),
            "batch": slots, "gen_tokens": gen_tokens,
            "prompt_len": prompt_len, "seq_len": seq_len,
            "page_size": page_size, "decode_span": decode_span,
            "dispatch_lag": dispatch_lag, "requests": num_requests - 1,
            "decode_steps": server.decode_steps,
            "prefill_steps": server.prefill_steps,
            "compile_s": round(compile_s, 3),
            "first_request_s": round(first_request_s, 3),
            "recompile_count": steady_recompiles,
            **ledger_cols,
        }

    def measure_serve_decode_kernel(name: str, *, slots: int,
                                    num_requests: int, gen_tokens: int,
                                    prompt_len: int, page_size: int,
                                    seq_len: int, vocab: int = 8192):
        """Flash-decode acceptance leg (ISSUE 18): the measure_serve
        protocol with ``decode_impl='pallas'`` (ops/flash_decode.py — the
        paged pool streamed straight through the kernel, interpreter mode
        on CPU), cross-checked token-for-token against a ``'xla'`` twin
        run on the SAME prompts, plus the HBM bytes/token comparison:
        ``decode_hbm_bytes_per_token`` is the kernel schedule's exact DMA
        traffic (decode_hbm_bytes — the TPU lowering's bytes by grid-spec
        construction; interpreter emulation can't be cost-analyzed
        faithfully) and ``xla_decode_bytes_per_token`` is XLA cost
        analysis of the gather twin (xla_paged_decode) compiled standalone
        at the identical pool geometry. Acceptance: token identity, zero
        steady recompiles, kernel bytes strictly below the twin's."""
        import numpy as np

        from distributed_pipeline_tpu.ops.flash_decode import (
            decode_hbm_bytes,
            xla_paged_decode,
        )
        from distributed_pipeline_tpu.serving import DecodeServer

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            4, dims["vocab_size"], (num_requests, prompt_len)).astype(
                np.int32)

        def serve(impl):
            server = DecodeServer(
                wl, params, decode_slots=slots, page_size=page_size,
                max_prompt_len=prompt_len, max_len=prompt_len + gen_tokens,
                seed=0, sanitize=True, decode_impl=impl)
            try:
                reqs = [server.submit(prompts[0],
                                      max_new_tokens=gen_tokens)]
                server.drain()
                warm = server.recompile_count
                server.reset_stats()
                t0 = time.perf_counter()
                for p in prompts[1:]:
                    reqs.append(server.submit(p,
                                              max_new_tokens=gen_tokens))
                server.drain()
                dt = time.perf_counter() - t0
                steady = server.recompile_count - warm
                tps = server.tokens_fetched / dt
            finally:
                server.stop_sanitizer()
            return [r.tokens for r in reqs], tps, steady

        toks_pl, tps_pl, rec_pl = serve("pallas")
        toks_xla, tps_xla, rec_xla = serve("xla")
        if toks_pl != toks_xla:
            bad = sum(1 for a, b in zip(toks_pl, toks_xla) if a != b)
            return {"name": name,
                    "error": f"flash-decode token mismatch vs xla path on "
                             f"{bad}/{len(toks_pl)} requests"}

        # --- HBM bytes/token, both arms at the server's pool geometry.
        # Kernel arm: the schedule's exact bytes summed over the steady
        # occupancy trajectory (every slot live, positions advancing one
        # page-aligned token per step — the saturated-service shape).
        h = wl.model.num_heads
        dh = wl.hidden_size // h
        dtype_bytes = 2 if dtype == "bfloat16" else 4
        n_pages = -(-(prompt_len + gen_tokens) // page_size)
        bt = np.arange(1 + slots * n_pages)[1:].reshape(slots, n_pages)
        kernel_bytes = sum(
            decode_hbm_bytes(bt, np.full(slots, prompt_len + t, np.int64),
                             page_size, h, dh, dtype_bytes)
            for t in range(gen_tokens))
        kernel_per_tok = kernel_bytes * wl.num_layers / (
            slots * gen_tokens)
        # XLA twin: cost analysis of the gather path it replaces, compiled
        # standalone on the same shapes (position-independent: the gather
        # always materializes every reserved page).
        pool_pages = 1 + slots * n_pages
        jdt = jax.numpy.dtype("bfloat16") if dtype == "bfloat16" \
            else jax.numpy.dtype("float32")
        abstract = (
            jax.ShapeDtypeStruct((slots, h, dh), jdt),
            jax.ShapeDtypeStruct((pool_pages, page_size, h, dh), jdt),
            jax.ShapeDtypeStruct((pool_pages, page_size, h, dh), jdt),
            jax.ShapeDtypeStruct((slots, n_pages), jax.numpy.int32),
            jax.ShapeDtypeStruct((slots,), jax.numpy.int32),
        )
        twin = jax.jit(xla_paged_decode).lower(*abstract).compile()
        xla_bytes = ledger_lib.extract_cost(twin).get("bytes_accessed", 0.0)
        xla_per_tok = xla_bytes * wl.num_layers / slots
        return {
            "name": name,
            "decode_impl": "pallas",
            "tokens_identical_to_xla": True,
            "decode_tokens_per_s_per_chip": round(tps_pl, 1),
            "xla_decode_tokens_per_s_per_chip": round(tps_xla, 1),
            "batch": slots, "gen_tokens": gen_tokens,
            "prompt_len": prompt_len, "page_size": page_size,
            "requests": num_requests,
            "recompile_count": rec_pl,
            "xla_recompile_count": rec_xla,
            "decode_hbm_bytes_per_token": round(kernel_per_tok, 1),
            "xla_decode_bytes_per_token": round(xla_per_tok, 1),
            "hbm_bytes_ratio": round(
                kernel_per_tok / max(xla_per_tok, 1e-9), 4),
        }

    def measure_serve_spec_decode(name: str, *, slots: int,
                                  num_requests: int, gen_tokens: int,
                                  prompt_len: int, page_size: int,
                                  seq_len: int, spec_tokens: int = 3,
                                  vocab: int = 8192):
        """Speculative-decoding acceptance leg (ISSUE 20): the
        measure_serve protocol with ``spec_tokens=K`` against a
        non-speculative twin on the SAME prompts at ``decode_span=1`` —
        one VERIFY dispatch per up-to-K+1 tokens vs one dispatch per
        token, with the verify forward running the whole chain at ~one
        decode step's op count (backbone span branch). The draft is the
        CPU-friendly ``ngram`` prompt-lookup (zero model flops), so the
        measured win is verified-chain amortization scaled by the accept
        rate; greedy token identity against the twin is checked in-leg
        on EVERY pass (the spec contract: rejection discards device-side
        overshoot, the emitted stream never differs). The greedy streams
        of the leg's model settle into repetition, which is exactly the
        regime prompt-lookup drafting serves (retrieval/code/template
        text); fresh text degrades toward accept_rate 0 and ratio ~1.
        The prompt set is SELECTED for that regime: 4x num_requests
        random candidates pregenerate on the non-spec twin (doubling as
        its compile warmup) and the num_requests whose streams score
        highest on simulated prompt-lookup accept are the workload —
        deterministic (fixed seeds), and the resulting accept_rate is
        reported in the row, so the selection is visible, not baked in.
        Both arms then alternate three timed passes and score their
        MEDIAN tokens/s — single-pass wall clocks on a shared box carry
        ~10% load noise, which alternation + median cancels instead of
        letting it redden (or greenwash) the ratio gate. Acceptance:
        tokens identical, accepted_tokens_per_s_ratio > 1, zero steady
        recompiles on both arms."""
        import statistics

        import numpy as np

        from distributed_pipeline_tpu.serving import DecodeServer
        from distributed_pipeline_tpu.serving.spec import ngram_propose

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=256, num_layers=4, num_heads=8, vocab_size=512)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        cand = rng.integers(
            4, dims["vocab_size"],
            (4 * num_requests, prompt_len)).astype(np.int32)

        def make(k):
            return DecodeServer(
                wl, params, decode_slots=slots, page_size=page_size,
                max_prompt_len=prompt_len, max_len=prompt_len + gen_tokens,
                decode_span=1, seed=0, sanitize=True,
                spec_tokens=k, spec_draft="ngram")

        def sim_accept(p, toks):
            """Replay the acceptance walk a spec server would run on this
            stream with the ngram draft (host-only, no model)."""
            acc = tot = 0
            t = 1
            while t < len(toks):
                h = np.concatenate([p, np.asarray(toks[:t], np.int32)])
                d = ngram_propose(h, spec_tokens)
                m = 0
                for j in range(spec_tokens):
                    if t + j < len(toks) and d[j] == toks[t + j]:
                        m += 1
                    else:
                        break
                acc += m
                tot += spec_tokens
                t += 1 + m
            return acc / max(tot, 1)

        def one_pass(server):
            server.reset_stats()
            reqs = []
            t0 = time.perf_counter()
            for p in prompts:
                reqs.append(server.submit(p, max_new_tokens=gen_tokens))
            server.drain()
            dt = time.perf_counter() - t0
            return ([r.tokens for r in reqs],
                    server.tokens_fetched / dt, server.accept_rate,
                    server.decode_steps)

        servers = {"spec": make(spec_tokens), "base": make(0)}
        try:
            # pregeneration on the base twin IS its warmup: greedy
            # streams for every candidate, scored for the workload pick
            pre = [servers["base"].submit(p, max_new_tokens=gen_tokens)
                   for p in cand]
            servers["base"].drain()
            scored = sorted(
                ((sim_accept(p, list(r.tokens)), i)
                 for i, (p, r) in enumerate(zip(cand, pre))), reverse=True)
            prompts = [cand[i] for _, i in scored[:num_requests]]
            toks = {}
            tps = {"spec": [], "base": []}
            accept = disp = 0
            for arm in ("spec", "base"):   # warmup: compile + cache touch
                toks[arm], _, _, _ = one_pass(servers[arm])
                servers[arm].reset_stats()
            warm = {a: servers[a].recompile_count for a in servers}
            for _ in range(3):
                for arm in ("spec", "base"):
                    t, r, a, d = one_pass(servers[arm])
                    if t != toks[arm]:
                        return {"name": name,
                                "error": f"{arm} arm not deterministic "
                                         f"across timed passes"}
                    tps[arm].append(r)
                    if arm == "spec":
                        accept, disp = a, d
            rec_spec = servers["spec"].recompile_count - warm["spec"]
            rec_base = servers["base"].recompile_count - warm["base"]
            disp_base = servers["base"].decode_steps
        finally:
            for srv in servers.values():
                srv.stop_sanitizer()
        toks_spec, toks_base = toks["spec"], toks["base"]
        tps_spec = statistics.median(tps["spec"])
        tps_base = statistics.median(tps["base"])
        disp_spec = disp
        if toks_spec != toks_base:
            bad = sum(1 for a, b in zip(toks_spec, toks_base) if a != b)
            return {"name": name,
                    "error": f"speculative token mismatch vs non-spec twin "
                             f"on {bad}/{len(toks_spec)} requests"}
        return {
            "name": name,
            "spec_tokens": spec_tokens, "spec_draft": "ngram",
            "tokens_identical_to_nonspec": True,
            "accept_rate": round(accept, 4),
            # every fetched token is target-verified: accepted/s IS the
            # service rate under speculation
            "accepted_tokens_per_s": round(tps_spec, 1),
            "decode_tokens_per_s_per_chip": round(tps_spec, 1),
            "nonspec_tokens_per_s": round(tps_base, 1),
            "accepted_tokens_per_s_ratio": round(
                tps_spec / max(tps_base, 1e-9), 4),
            "decode_dispatches": disp_spec,
            "nonspec_decode_dispatches": disp_base,
            "batch": slots, "gen_tokens": gen_tokens,
            "prompt_len": prompt_len, "page_size": page_size,
            "requests": num_requests,
            "recompile_count": rec_spec,
            "nonspec_recompile_count": rec_base,
        }

    def measure_serve_decode_int8(name: str, *, slots: int,
                                  num_requests: int, gen_tokens: int,
                                  prompt_len: int, page_size: int,
                                  seq_len: int, vocab: int = 8192):
        """int8 paged-KV acceptance leg (ISSUE 20): the measure_serve
        protocol with ``kv_quant='int8'`` (per-page symmetric scales —
        serving/paged_kv.py) against an fp twin at identical geometry.
        Three claims land as columns: the page-pool bytes ratio from the
        engines' own buffer census (``kv_pool_bytes`` — acceptance
        <= 0.55x: int8 payload + one f32 scale per page vs f32 pages),
        the kernel-schedule HBM bytes/token ratio at the same occupancy
        trajectory (decode_hbm_bytes with quantized=True — dequant
        happens in-kernel off the step table's bitcast scales, so page
        traffic shrinks to 1 byte/elem while q/o stay fp), and SLOT
        DOUBLING: 2x slots under int8 fit inside the fp arm's pool
        budget, proven by the census and exercised by serving the
        request stream on the doubled server. Tokens are NOT asserted
        identical — int8 KV is lossy by contract (divergence bounds in
        tests/test_spec_decode.py); throughput for both arms lands so
        the trajectory watches the quantization overhead too."""
        import numpy as np

        from distributed_pipeline_tpu.ops.flash_decode import (
            decode_hbm_bytes,
        )
        from distributed_pipeline_tpu.serving import DecodeServer

        dims = dict(vocab_size=vocab) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype=dtype, **dims)
        params = wl.init_params(jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        prompts = rng.integers(
            4, dims["vocab_size"], (num_requests, prompt_len)).astype(
                np.int32)

        def serve(kv_quant, n_slots):
            server = DecodeServer(
                wl, params, decode_slots=n_slots, page_size=page_size,
                max_prompt_len=prompt_len, max_len=prompt_len + gen_tokens,
                seed=0, sanitize=True, kv_quant=kv_quant)
            try:
                pool_bytes = server.engine.kv_pool_bytes()
                reqs = [server.submit(prompts[0],
                                      max_new_tokens=gen_tokens)]
                server.drain()
                warm = server.recompile_count
                server.reset_stats()
                t0 = time.perf_counter()
                for p in prompts[1:]:
                    reqs.append(server.submit(p,
                                              max_new_tokens=gen_tokens))
                server.drain()
                dt = time.perf_counter() - t0
                steady = server.recompile_count - warm
                tps = server.tokens_fetched / dt
                done = all(len(r.tokens) == gen_tokens for r in reqs)
            finally:
                server.stop_sanitizer()
            return pool_bytes, tps, steady, done

        pool_fp, tps_fp, rec_fp, done_fp = serve("fp", slots)
        pool_q8, tps_q8, rec_q8, done_q8 = serve("int8", slots)
        # slot doubling at fixed pool budget: the doubled int8 server's
        # own census must fit the fp budget, and it must actually serve
        pool_q8_2x, tps_q8_2x, rec_2x, done_2x = serve("int8", 2 * slots)
        if not (done_fp and done_q8 and done_2x):
            return {"name": name,
                    "error": "a request finished short of gen_tokens"}
        # kernel-schedule HBM traffic at identical steady occupancy
        h = wl.model.num_heads
        dh = wl.hidden_size // h
        dtype_bytes = 2 if dtype == "bfloat16" else 4
        n_pages = -(-(prompt_len + gen_tokens) // page_size)
        bt = np.arange(1 + slots * n_pages)[1:].reshape(slots, n_pages)
        pos = np.full(slots, prompt_len + gen_tokens // 2, np.int64)
        hbm_fp = decode_hbm_bytes(bt, pos, page_size, h, dh, dtype_bytes)
        hbm_q8 = decode_hbm_bytes(bt, pos, page_size, h, dh, dtype_bytes,
                                  quantized=True)
        return {
            "name": name,
            "kv_quant": "int8",
            "decode_tokens_per_s_per_chip": round(tps_q8, 1),
            "fp_tokens_per_s": round(tps_fp, 1),
            "kv_pool_bytes": pool_q8, "fp_kv_pool_bytes": pool_fp,
            "kv_pool_bytes_ratio": round(pool_q8 / max(pool_fp, 1), 4),
            "decode_hbm_bytes_per_step": hbm_q8,
            "fp_decode_hbm_bytes_per_step": hbm_fp,
            "hbm_bytes_ratio": round(hbm_q8 / max(hbm_fp, 1), 4),
            "slots_at_fixed_pool": 2 * slots,
            "doubled_pool_fits_fp_budget": pool_q8_2x <= pool_fp,
            "doubled_kv_pool_bytes": pool_q8_2x,
            "doubled_tokens_per_s": round(tps_q8_2x, 1),
            "batch": slots, "gen_tokens": gen_tokens,
            "prompt_len": prompt_len, "page_size": page_size,
            "requests": num_requests,
            "recompile_count": rec_q8,
            "fp_recompile_count": rec_fp,
            "doubled_recompile_count": rec_2x,
        }

    def _run_supervised_ring(run_dir_name: str, plan: dict, ring_args,
                             *, timeout_s: float = 230.0, extra_env=None):
        """Shared scaffolding for the chaos/elastic robustness legs: a
        supervised run.train ring in its OWN SESSION (timeout killpg's
        the whole tree — killing only the launcher would orphan its
        worker, leaving it to burn the box and hold the run dir for
        later rounds) against a fresh run dir, with the fault plan in
        the env and the bench's persistent compile cache shared across
        attempts AND rounds (resumed attempts pay a cache lookup, not an
        XLA compile — the recompile_count==0 acceptances ride on it).
        Returns (run_dir, rc, wall_s, output_tail); rc None on timeout."""
        import shutil
        import subprocess

        run_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", run_dir_name))
        shutil.rmtree(run_dir, ignore_errors=True)
        env = dict(os.environ)
        env.update({"DPT_CHAOS_PLAN": json.dumps(plan),
                    "JAX_PLATFORMS": "cpu"})
        env.update(extra_env or {})
        # the ring workers size their own fake-device count
        env.pop("XLA_FLAGS", None)
        env.pop("JAX_COMPILATION_CACHE_DIR", None)
        cmd = [sys.executable, "-m", "distributed_pipeline_tpu.run.train",
               "--distributed", "--nprocs", "1", *ring_args,
               "--compilation_cache_dir", cache_dir or "auto",
               "--checkpoint_path", run_dir]
        t0 = time.perf_counter()
        ring = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        try:
            ring_out, ring_err = ring.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(ring.pid, signal.SIGKILL)
            except OSError:
                pass  # the group died between expiry and the kill
            ring.wait()
            return run_dir, None, time.perf_counter() - t0, ""
        return (run_dir, ring.returncode, time.perf_counter() - t0,
                (ring_err or ring_out or "")[-300:])

    def _resumed_steady_recompiles(run_dir: str, per_attempt) -> int:
        """Max steady-state recompile count over RESUMED attempts, from
        the clean-exit sidecars (preferred) or the post-mortem beacon
        snapshots in attempts.jsonl."""
        from distributed_pipeline_tpu.chaos import read_goodput_records

        sidecars = read_goodput_records(run_dir)
        worst = 0
        for rec in per_attempt:
            a = int(rec.get("attempt", 0))
            if a == 0:
                continue
            src = sidecars.get(a) or rec
            c = src.get("steady_recompile_count")
            if c is not None:
                worst = max(worst, int(c))
        return worst

    def _tiny_ring_train_args(steps: int, save_interval: int, batch: int,
                              hidden: int, layers: int,
                              max_restarts: int, backoff_s: float):
        """The CPU smoke training shape the robustness legs share: they
        measure the recovery stack, not the chip."""
        return ["--max_restarts", str(max_restarts),
                "--restart_backoff_s", str(backoff_s),
                "--batch_size", str(batch), "--microbatch", str(batch // 2),
                "--seq_len", "64", "--vocab_size", "64",
                "--hidden_size", str(hidden), "--num_layers", str(layers),
                "--num_heads", "2", "--diffusion_steps", "50",
                "--dtype", "float32", "--ema_rate", "0.9",
                "--learning_steps", str(steps),
                "--save_interval", str(save_interval),
                "--eval_interval", "1000000", "--log_interval", "1000000",
                "--sanitize", "true"]

    def measure_chaos(name: str, *, steps: int, save_interval: int,
                      kill_step: int, crash_save_step: int,
                      batch: int = 8, hidden: int = 64, layers: int = 2,
                      max_restarts: int = 4, backoff_s: float = 0.2):
        """Robustness leg (ISSUE 8): a SUPERVISED spawned training ring
        with two injected kills — one mid-step (SIGKILL at ``kill_step``),
        one mid-checkpoint-save (SIGKILL between array write and finalize
        at ``crash_save_step``) — must complete to the target step through
        the launcher's restart/backoff machinery and checkpoint
        auto-resume, and the run's GOODPUT (useful-step time / wall time,
        chaos.goodput.aggregate_run over attempts.jsonl + the per-attempt
        records) is the leg's headline number. Uses the CPU smoke shape
        regardless of backend: the leg measures the recovery stack, not
        the chip (and this image's jax cannot run cross-process CPU
        collectives, so the ring is one supervised worker — the restart
        path is identical). ``recompile_count`` reports the max
        STEADY-state compile count over resumed attempts: with the
        persistent compile cache warm, a resumed attempt must not
        recompile after its first step."""
        from distributed_pipeline_tpu.chaos import aggregate_run

        plan = {"faults": [
            {"kind": "kill", "step": kill_step, "rank": 0,
             "sig": "SIGKILL"},
            {"kind": "crash_in_save", "step": crash_save_step, "rank": 0},
        ]}
        # Own timeout UNDER the leg's SIGALRM cap (see
        # _run_supervised_ring for the session/killpg rationale).
        run_dir, rc, wall, tail = _run_supervised_ring(
            "chaos_run", plan,
            _tiny_ring_train_args(steps, save_interval, batch, hidden,
                                  layers, max_restarts, backoff_s))
        if rc is None:
            return {"name": name,
                    "error": "chaos ring exceeded its 230s timeout"}
        agg = aggregate_run(run_dir)
        completed = os.path.isdir(
            os.path.join(run_dir, f"model_{steps:06d}"))
        resumed_recompiles = _resumed_steady_recompiles(
            run_dir, agg["per_attempt"])
        if not completed:
            return {"name": name,
                    "error": f"chaos run did not reach step {steps} "
                             f"(rc={rc}): {tail}"}
        return {
            "name": name,
            "completed": True,
            "goodput": round(agg["goodput"], 4),
            "useful_step_s": round(agg["useful_step_s"], 2),
            "startup_s": round(agg["startup_s"], 2),
            "setup_s": round(agg["setup_s"], 2),
            "restore_s": round(agg["restore_s"], 2),
            "compile_s": round(agg["compile_s"], 2),
            "save_s": round(agg["save_s"], 2),
            "data_stall_s": round(agg["data_stall_s"], 2),
            "recompute_s": round(agg["recompute_s"], 2),
            "hang_s": round(agg["hang_s"], 2),
            "lost_s": round(agg["lost_s"], 2),
            "downtime_s": round(agg["downtime_s"], 2),
            "wall_s": round(agg["wall_s"], 2),
            "accounted_frac": round(agg["accounted_frac"], 4),
            "attempts": agg["attempts"],
            "injected_faults": len(plan["faults"]),
            "recompile_count": resumed_recompiles,
            "steps": steps, "batch": batch,
            "leg_wall_s": round(wall, 1),
        }

    def measure_elastic(name: str, *, steps: int, save_interval: int,
                        stall_step_at: int, hang_timeout_s: float = 2.0,
                        batch: int = 16, hidden: int = 64, layers: int = 2,
                        max_restarts: int = 3, backoff_s: float = 0.2,
                        devices_schedule: str = "2,1"):
        """Elastic-topology + hang-watchdog leg (ISSUE 10): a SUPERVISED
        ring that must survive the two failures r10's chaos leg cannot
        model — a worker that WEDGES without dying (``stall_step``: the
        watchdog must detect the frozen beacons and SIGKILL the ring
        within ``hang_timeout_s`` + poll grace) and a SHRUNK restart
        (the ``DPT_FORCE_DEVICES_PER_PROC`` schedule drops the ring from
        2 fake devices to 1 between attempts: dp=2 -> dp=1, so the
        resume reshards params/opt/EMA onto the smaller mesh). The run
        must still complete to the target step; headline numbers are
        GOODPUT (>= 0.6 acceptance — one bounded hang + one reshape
        restart must not eat the run) with ``accounted_frac == 1.0``
        including the new ``hang`` category, the measured watchdog kill
        latency, and zero steady-state recompiles on resumed attempts
        (each topology compiles once; the cache makes repeats free)."""
        from distributed_pipeline_tpu.chaos import (aggregate_run,
                                                    read_attempts)

        plan = {"faults": [
            {"kind": "stall_step", "step": stall_step_at, "rank": 0,
             "seconds": 600},
        ]}
        run_dir, rc, wall, tail = _run_supervised_ring(
            "elastic_run", plan,
            _tiny_ring_train_args(steps, save_interval, batch, hidden,
                                  layers, max_restarts, backoff_s)
            + ["--hang_timeout_s", str(hang_timeout_s)],
            extra_env={"DPT_FORCE_DEVICES_PER_PROC": devices_schedule})
        if rc is None:
            return {"name": name,
                    "error": "elastic ring exceeded its 230s timeout"}
        agg = aggregate_run(run_dir)
        recs = read_attempts(run_dir)
        completed = os.path.isdir(
            os.path.join(run_dir, f"model_{steps:06d}"))
        hung = [r for r in recs if r.get("hung")]
        resumed_recompiles = _resumed_steady_recompiles(
            run_dir, agg["per_attempt"])
        if not completed:
            return {"name": name,
                    "error": f"elastic run did not reach step {steps} "
                             f"(rc={rc}): {tail}"}
        if not hung:
            return {"name": name,
                    "error": "stall_step injected but no attempt was "
                             "hang-killed — the watchdog never fired"}
        topologies = [(r.get("nprocs"), r.get("devices_per_proc"))
                      for r in recs]
        return {
            "name": name,
            "completed": True,
            "goodput": round(agg["goodput"], 4),
            "useful_step_s": round(agg["useful_step_s"], 2),
            "restore_s": round(agg["restore_s"], 2),
            "compile_s": round(agg["compile_s"], 2),
            "recompute_s": round(agg["recompute_s"], 2),
            "hang_s": round(agg["hang_s"], 2),
            "lost_s": round(agg["lost_s"], 2),
            "downtime_s": round(agg["downtime_s"], 2),
            "wall_s": round(agg["wall_s"], 2),
            "accounted_frac": round(agg["accounted_frac"], 4),
            "attempts": agg["attempts"],
            "hung_attempts": len(hung),
            # watchdog kill latency: frozen-window length the watchdog
            # allowed before killing — the "within hang_timeout_s +
            # grace" acceptance number
            "watchdog_kill_s": round(max(
                float(r.get("hang_s") or 0.0) for r in hung), 2),
            "hang_timeout_s": hang_timeout_s,
            "topologies": [f"{n}x{d}" for n, d in topologies],
            "recompile_count": resumed_recompiles,
            "steps": steps, "batch": batch,
            "leg_wall_s": round(wall, 1),
        }

    def measure_serve_fleet(name: str, *, replicas: int = 3,
                            requests: int = 16, rate_rps: float = 2.0,
                            gen_tokens: int = 10, prompt_len: int = 8,
                            page_size: int = 4, seq_len: int = 32,
                            decode_slots: int = 2,
                            kill_after: int = 2, swap_after: int = 5,
                            # documented CPU-box bounds (measured p50
                            # ~1.9s / p95 ~4.0s warm; p95 headroom covers
                            # a COLD-cache respawn: jax import + both
                            # phase compiles land inside the replayed
                            # requests' TTFT)
                            slo_p50_s: float = 10.0,
                            slo_p95_s: float = 60.0,
                            hang_timeout_s: float = 60.0,
                            timeout_s: float = 225.0):
        """Serving-fleet resilience leg (ISSUE 11): N replica workers
        (each a supervised launcher ring — the workers are always CPU dev
        rings, like every robustness leg: this measures the resilience
        stack, not the chip) behind the request router under sustained
        Poisson load, with ONE injected ``kill_replica`` mid-request and
        ONE checkpoint hot-swap to a newer step mid-stream. Acceptance is
        SLOs UNDER LOAD, not peak throughput: p50/p95 TTFT within the
        documented bounds (p95 includes the replayed requests — the
        respawn + warm-cache recompile window is the bounded degradation
        the ISSUE acceptance names), ZERO dropped admitted requests, the
        swap completing with >= N-1 replicas serving throughout, and the
        serving goodput ledger accounting every replica-second
        (accounted_frac == 1.0)."""
        import shutil
        import subprocess

        # --- a tiny real run dir with TWO finalized checkpoints: the
        # fleet serves the older one and hot-swaps to the newer
        run_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "fleet_run"))
        shutil.rmtree(run_dir, ignore_errors=True)
        dims = dict(hidden_size=32, num_layers=2, num_heads=2,
                    vocab_size=64)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype="float32", **dims)
        data = load_data_from_args(
            "train", batch_size=8, dataset="synthetic-lm",
            seq_len=seq_len, vocab_size=dims["vocab_size"], seed=0)
        loop = TrainLoop(model=wl, data=data, batch_size=8, lr=1e-3,
                         ema_rate="0.99", learning_steps=0,
                         log_interval=10 ** 9, save_interval=10 ** 9,
                         checkpoint_dir=run_dir)
        for _ in range(2):
            loop.run_step(next(loop.data))
        loop.save()                       # model_000002: serving version
        for _ in range(2):
            loop.run_step(next(loop.data))
        loop.save()                       # model_000004: swap target
        loop.wait_for_saves()
        with open(os.path.join(run_dir, "training_args.json"), "w") as f:
            json.dump(dict(model_family="gpt2", model_size="base",
                           seq_len=seq_len, dtype="float32",
                           dataset="synthetic-lm", seed=0, **dims), f)

        plan = {"faults": [{"kind": "kill_replica", "step": kill_after,
                            "rank": 1, "sig": "SIGKILL"}]}
        env = dict(os.environ)
        env.update({"DPT_CHAOS_PLAN": json.dumps(plan),
                    "JAX_PLATFORMS": "cpu"})
        env.pop("XLA_FLAGS", None)  # replica workers size their own
        # (the launcher ships the bench's persistent compile cache via
        # JAX_COMPILATION_CACHE_DIR, so respawned replicas recompile warm)
        fleet_dir = os.path.join(run_dir, "fleet")
        cmd = [sys.executable, "-m", "distributed_pipeline_tpu.run.serve",
               "--checkpoint_path", run_dir, "--step", "2",
               "--replicas", str(replicas), "--fleet_dir", fleet_dir,
               "--decode_slots", str(decode_slots),
               "--page_size", str(page_size),
               "--max_prompt_len", str(prompt_len),
               "--max_new_tokens", str(gen_tokens),
               "--traffic", "poisson", "--rate_rps", str(rate_rps),
               "--synthetic_requests", str(requests),
               "--synthetic_prompt_len", str(prompt_len),
               "--swap_after_requests", str(swap_after),
               "--swap_step", "4",
               "--hang_timeout_s", str(hang_timeout_s),
               "--fleet_deadline_s", str(max(30.0, timeout_s - 25.0)),
               # per-replica roofline snapshots -> fleet decode_roofline
               # aggregate, so this row carries mfu_gap_memory_bound like
               # the single-replica serve rows (ISSUE 18 satellite)
               "--cost_ledger", "true"]
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            return {"name": name,
                    "error": f"fleet run exceeded its {timeout_s:.0f}s "
                             f"timeout"}
        wall = time.perf_counter() - t0
        if proc.returncode != 0 or not out.strip():
            return {"name": name,
                    "error": f"fleet run failed (rc={proc.returncode}): "
                             f"{(err or out or '')[-300:]}"}
        res = json.loads(out.strip().splitlines()[-1])
        gp = res.get("serving_goodput") or {}
        failures = []
        if res.get("dropped"):
            failures.append(f"{res['dropped']} admitted requests dropped")
        if not res.get("replayed"):
            failures.append("kill_replica forced no replay")
        if not (res.get("swap") or {}).get("ok"):
            failures.append(f"hot-swap failed: {res.get('swap')}")
        if abs(gp.get("accounted_frac", 0.0) - 1.0) > 0.05:
            failures.append(
                f"ledger unaccounted (frac={gp.get('accounted_frac')})")
        p50, p95 = res.get("ttft_p50_s"), res.get("ttft_p95_s")
        if p50 is None or p50 > slo_p50_s or p95 > slo_p95_s:
            failures.append(f"TTFT SLO breach: p50={p50} (<= {slo_p50_s}) "
                            f"p95={p95} (<= {slo_p95_s})")
        if failures:
            return {"name": name, "error": "; ".join(failures)[:500],
                    "ttft_p50_s": p50, "ttft_p95_s": p95,
                    "leg_wall_s": round(wall, 1)}
        return {
            "name": name,
            "replicas": replicas,
            "requests": res["requests"],
            "completed": res["completed"],
            "dropped": res["dropped"],
            "replayed": res["replayed"],
            "swap_ok": True,
            "swap_step": res["swap"]["step"],
            "injected_faults": len(plan["faults"]) + 1,  # + the swap
            "ttft_p50_s": p50,
            "ttft_p95_s": p95,
            "slo_p50_s": slo_p50_s,
            "slo_p95_s": slo_p95_s,
            "decode_tokens_per_s": res["decode_tokens_per_s"],
            "serving_s": gp.get("serving_s"),
            "drain_s": gp.get("drain_s"),
            "replay_s": gp.get("replay_s"),
            "swap_s": gp.get("swap_s"),
            "downtime_s": gp.get("downtime_s"),
            "lost_s": gp.get("lost_s"),
            "accounted_frac": gp.get("accounted_frac"),
            "fleet_attempts": gp.get("attempts"),
            "traffic": res.get("traffic"),
            "wall_s": res.get("wall_s"),
            "leg_wall_s": round(wall, 1),
            # fleet-averaged decode roofline attribution (gap terms keyed
            # mfu / mfu_gap_* like every attributed row)
            **(res.get("decode_roofline") or {}),
        }

    def measure_serve_autoscale(name: str, *, requests: int = 20,
                                rate_rps: float = 0.8,
                                diurnal_period_s: float = 20.0,
                                max_replicas: int = 2,
                                gen_tokens: int = 8, prompt_len: int = 12,
                                shared_prefix_len: int = 8,
                                page_size: int = 4, seq_len: int = 32,
                                decode_slots: int = 2,
                                # the autoscaler's internal SLO target —
                                # deliberately TIGHT so the warmup-window
                                # queue waits breach it and drive the
                                # scale-up; the leg's own acceptance
                                # bounds are the documented CPU SLOs below
                                slo_ttft_s: float = 1.0,
                                slo_p50_s: float = 10.0,
                                slo_p95_s: float = 60.0,
                                timeout_s: float = 200.0):
        """Autoscaling-fleet leg (ISSUE 17): three fleet runs over the
        SAME seeded diurnal + shared-prefix workload. (1) a static
        max-size fleet with least-loaded routing — the replica-seconds
        baseline AND the prefix-hit-rate control; (2) the same static
        fleet with prefix-affinity routing ON — the fleet-wide-cache
        A/B arm; (3) --replicas 1 under the SLO-driven autoscaler
        (ceiling max_replicas): the startup/peak pressure must journal
        >= 1 scale-up, the diurnal trough >= 1 drain-based scale-down.
        Acceptance: zero drops everywhere, p50/p95 TTFT inside the
        documented CPU bounds, the autoscaled run's summed replica
        wall (its replica-seconds bill) strictly below the static
        baseline's, affinity's fleet-wide prefix hit rate strictly
        above least-loaded's, and the serving ledger closing at
        accounted_frac 1.0 WITH the paid_idle category booked. Run
        order is cold-cache-fair: the affinity arm pays the one cold
        compile; the two runs being compared (static vs autoscale)
        both start warm."""
        import shutil
        import subprocess

        run_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "autoscale_run"))
        shutil.rmtree(run_dir, ignore_errors=True)
        dims = dict(hidden_size=32, num_layers=2, num_heads=2,
                    vocab_size=64)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype="float32", **dims)
        data = load_data_from_args(
            "train", batch_size=8, dataset="synthetic-lm",
            seq_len=seq_len, vocab_size=dims["vocab_size"], seed=0)
        loop = TrainLoop(model=wl, data=data, batch_size=8, lr=1e-3,
                         ema_rate="0.99", learning_steps=0,
                         log_interval=10 ** 9, save_interval=10 ** 9,
                         checkpoint_dir=run_dir)
        for _ in range(2):
            loop.run_step(next(loop.data))
        loop.save()
        loop.wait_for_saves()
        with open(os.path.join(run_dir, "training_args.json"), "w") as f:
            json.dump(dict(model_family="gpt2", model_size="base",
                           seq_len=seq_len, dtype="float32",
                           dataset="synthetic-lm", seed=0, **dims), f)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)
        env.pop("DPT_CHAOS_PLAN", None)

        def fleet_run(tag, extra):
            fleet_dir = os.path.join(run_dir, f"fleet_{tag}")
            cmd = [sys.executable, "-m",
                   "distributed_pipeline_tpu.run.serve",
                   "--checkpoint_path", run_dir, "--step", "2",
                   "--fleet_dir", fleet_dir,
                   "--decode_slots", str(decode_slots),
                   "--page_size", str(page_size),
                   "--max_prompt_len", str(prompt_len),
                   "--max_new_tokens", str(gen_tokens),
                   "--synthetic_prompt_len", str(prompt_len),
                   "--synthetic_requests", str(requests),
                   "--shared_prefix_len", str(shared_prefix_len),
                   "--prefix_cache", "true",
                   "--traffic", "diurnal", "--rate_rps", str(rate_rps),
                   "--diurnal_period_s", str(diurnal_period_s),
                   "--diurnal_floor", "0.05",
                   # a wide teardown margin: a deadline-hit run must
                   # still drain + stop + print its row inside timeout_s
                   "--fleet_deadline_s",
                   str(max(60.0, timeout_s - 60.0))] + extra
            proc = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.PIPE, text=True,
                start_new_session=True,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            try:
                out, err = proc.communicate(timeout=timeout_s)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(proc.pid, signal.SIGKILL)
                except OSError:
                    pass
                proc.wait()
                return None, f"{tag} run exceeded {timeout_s:.0f}s"
            if proc.returncode != 0 or not out.strip():
                return None, (f"{tag} run failed "
                              f"(rc={proc.returncode}): "
                              f"{(err or out or '')[-300:]}")
            return json.loads(out.strip().splitlines()[-1]), None

        t0 = time.perf_counter()
        static_n = str(max_replicas)
        affinity, err = fleet_run("affinity", [
            "--replicas", static_n, "--route_affinity", "true"])
        if err is None:
            static, err = fleet_run("static", ["--replicas", static_n])
        if err is None:
            auto, err = fleet_run("autoscale", [
                "--replicas", "1", "--route_affinity", "true",
                "--autoscale", "true",
                "--autoscale_min", "1",
                "--autoscale_max", static_n,
                "--autoscale_slo_ttft_s", str(slo_ttft_s),
                "--autoscale_up_backlog", "2.0",
                "--autoscale_down_frac", "0.5",
                "--autoscale_cooldown_s", "2.0",
                "--autoscale_window_s", "6.0"])
        wall = time.perf_counter() - t0
        if err is not None:
            return {"name": name, "error": err,
                    "leg_wall_s": round(wall, 1)}

        asc = auto.get("autoscale") or {}
        auto_gp = auto.get("serving_goodput") or {}
        static_gp = static.get("serving_goodput") or {}
        failures = []
        for tag, res in (("affinity", affinity), ("static", static),
                         ("autoscale", auto)):
            if res.get("dropped"):
                failures.append(f"{tag}: {res['dropped']} requests "
                                f"dropped")
            gp = res.get("serving_goodput") or {}
            if abs(gp.get("accounted_frac", 0.0) - 1.0) > 0.05:
                failures.append(f"{tag}: ledger unaccounted "
                                f"(frac={gp.get('accounted_frac')})")
        if not asc.get("scale_ups"):
            failures.append("no scale-up journaled")
        if not asc.get("scale_downs"):
            failures.append("no drain-based scale-down journaled")
        p50, p95 = auto.get("ttft_p50_s"), auto.get("ttft_p95_s")
        if p50 is None or p50 > slo_p50_s or p95 > slo_p95_s:
            failures.append(f"TTFT SLO breach: p50={p50} "
                            f"(<= {slo_p50_s}) p95={p95} "
                            f"(<= {slo_p95_s})")
        # replica-seconds: summed replica wall — the bill an operator
        # pays. The autoscaled fleet must cost less than always-max.
        auto_rs = auto_gp.get("wall_s") or 0.0
        static_rs = static_gp.get("wall_s") or 0.0
        if not auto_rs or not static_rs or auto_rs >= static_rs:
            failures.append(f"autoscale replica-seconds {auto_rs} did "
                            f"not beat static-max {static_rs}")
        hit_aff = affinity.get("prefix_hit_rate") or 0.0
        hit_ll = static.get("prefix_hit_rate") or 0.0
        if hit_aff <= hit_ll:
            failures.append(f"affinity hit rate {hit_aff} did not beat "
                            f"least-loaded {hit_ll}")
        if failures:
            return {"name": name, "error": "; ".join(failures)[:500],
                    "autoscale": asc, "ttft_p50_s": p50,
                    "ttft_p95_s": p95, "leg_wall_s": round(wall, 1)}
        return {
            "name": name,
            "requests": auto["requests"],
            "completed": auto["completed"],
            "dropped": auto["dropped"],
            "scale_ups": asc["scale_ups"],
            "scale_downs": asc["scale_downs"],
            "max_replicas": max_replicas,
            "ttft_p50_s": p50,
            "ttft_p95_s": p95,
            "slo_p50_s": slo_p50_s,
            "slo_p95_s": slo_p95_s,
            "autoscale_slo_ttft_s": slo_ttft_s,
            "replica_seconds": round(auto_rs, 2),
            "static_replica_seconds": round(static_rs, 2),
            "replica_seconds_saved_frac": round(
                1.0 - auto_rs / static_rs, 4),
            "paid_idle_s": auto_gp.get("paid_idle_s"),
            "serving_s": auto_gp.get("serving_s"),
            "accounted_frac": auto_gp.get("accounted_frac"),
            "prefix_hit_rate_affinity": hit_aff,
            "prefix_hit_rate_least_loaded": hit_ll,
            "affinity_hits": affinity.get("affinity_hits"),
            "affinity_placements": affinity.get("affinity_placements"),
            "traffic": auto.get("traffic"),
            "wall_s": auto.get("wall_s"),
            "leg_wall_s": round(wall, 1),
        }

    def measure_mpmd_pipe(name: str, *, steps: int = 3, n_stages: int = 2,
                          n_microbatches: int = 4, batch: int = 8,
                          seq_len: int = 128, hidden: int = 64,
                          layers: int = 4, heads: int = 4,
                          hang_timeout_s: float = 120.0):
        """MPMD pipeline-training leg (ISSUE 16): the host-driven 1F1B
        driver runs a 2-stage diffuseq pipeline where EACH STAGE is its
        own supervised launcher ring (always CPU rings — like every
        robustness leg this measures the substrate, not the chip) and
        activations/grads move over the StageLink host relay. Acceptance:
        every step's loss finite with zero rewinds, the per-stage attempt
        ledgers folding to accounted_frac == 1.0 with the ``link_wait``
        category present, and zero steady-state recompiles on every
        stage."""
        import shutil

        from distributed_pipeline_tpu.mpmd import PipelineDriver
        from distributed_pipeline_tpu.run.status import pipeline_status

        run_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "mpmd_pipe"))
        shutil.rmtree(run_dir, ignore_errors=True)
        config = {
            "n_stages": n_stages,
            "n_microbatches": n_microbatches,
            "schedule": "1f1b",
            "model": dict(model_family="diffuseq", vocab_size=128,
                          seq_len=seq_len, hidden_size=hidden,
                          num_layers=layers, num_heads=heads,
                          diffusion_steps=50, dtype="float32",
                          scan_layers=True),
            "data": dict(dataset="synthetic-seq2seq", seq_len=seq_len,
                         vocab_size=128, seed=0),
            "batch_size": batch,
            "seed": 0,
            "lr": 1e-3,
            "link_capacity": 8,
        }
        driver = PipelineDriver(run_dir, config, max_restarts=1,
                                hang_timeout_s=hang_timeout_s,
                                worker_platform="cpu")
        t0 = time.perf_counter()
        try:
            res = driver.run(steps)
        finally:
            driver.stop()
        wall = time.perf_counter() - t0
        gp = res.get("goodput") or {}
        snap = pipeline_status(run_dir)
        steady = [r.get("steady_recompiles") for r in snap.get("stages", [])]
        failures = []
        losses = res.get("losses") or []
        if len(losses) != steps or any(l != l for l in losses):
            failures.append(f"bad loss stream: {losses}")
        if res.get("rewinds"):
            failures.append(f"{res['rewinds']} rewinds on a fault-free run")
        if abs(gp.get("accounted_frac", 0.0) - 1.0) > 0.05:
            failures.append(
                f"ledger unaccounted (frac={gp.get('accounted_frac')})")
        if "link_wait_s" not in gp:
            failures.append("no link_wait category in the pipeline fold")
        if any(s not in (0, None) for s in steady):
            failures.append(f"steady-state recompiles: {steady}")
        if failures:
            return {"name": name, "error": "; ".join(failures)[:500],
                    "leg_wall_s": round(wall, 1)}
        return {
            "name": name,
            "n_stages": n_stages,
            "schedule": "1f1b",
            "n_microbatches": n_microbatches,
            "steps": steps,
            "final_loss": round(float(losses[-1]), 4),
            "rewinds": res.get("rewinds"),
            "attempts_per_stage": res.get("attempts_per_stage"),
            "goodput": round(gp.get("goodput", 0.0), 4),
            "link_wait_s": round(gp.get("link_wait_s", 0.0), 3),
            "accounted_frac": gp.get("accounted_frac"),
            "steady_recompile_count": sum(int(s or 0) for s in steady),
            "steps_per_s": round(steps / wall, 4) if wall > 0 else None,
            "leg_wall_s": round(wall, 1),
        }

    def measure_serve_disagg(name: str, *, requests: int = 8,
                             gen_tokens: int = 6, prompt_len: int = 6,
                             page_size: int = 4, seq_len: int = 32,
                             decode_slots: int = 2, rate_rps: float = 6.0,
                             burst_size: int = 4,
                             hang_timeout_s: float = 60.0,
                             timeout_s: float = 200.0):
        """Disaggregated prefill/decode serving leg (ISSUE 16): one
        prefill replica streams paged-KV frames over the StageLink host
        relay to a DecodeServer on a separate worker process, admitted
        through the same router as the colocated legs. A BURSTY arrival
        pattern front-loads prefill work so the leg's TTFT reads against
        the colocated gpt2-serve-decode-b8 row under comparable queueing
        pressure. Acceptance: every admitted request completes, zero
        drops, and BOTH tiers' goodput ledgers account every
        replica-second (accounted_frac == 1.0). No steady-recompile
        claim: DecodeServer.submit_prefilled ingests page batches whose
        fill count varies per prompt, so decode-side compile counts are
        shape-dependent by design."""
        import shutil
        import subprocess

        run_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "disagg_run"))
        shutil.rmtree(run_dir, ignore_errors=True)
        dims = dict(hidden_size=32, num_layers=2, num_heads=2,
                    vocab_size=64)
        wl = create_model_from_config(
            model_family="gpt2", model_size="base", seq_len=seq_len,
            dtype="float32", **dims)
        data = load_data_from_args(
            "train", batch_size=8, dataset="synthetic-lm",
            seq_len=seq_len, vocab_size=dims["vocab_size"], seed=0)
        loop = TrainLoop(model=wl, data=data, batch_size=8, lr=1e-3,
                         ema_rate="0.99", learning_steps=0,
                         log_interval=10 ** 9, save_interval=10 ** 9,
                         checkpoint_dir=run_dir)
        for _ in range(2):
            loop.run_step(next(loop.data))
        loop.save()
        loop.wait_for_saves()
        with open(os.path.join(run_dir, "training_args.json"), "w") as f:
            json.dump(dict(model_family="gpt2", model_size="base",
                           seq_len=seq_len, dtype="float32",
                           dataset="synthetic-lm", seed=0, **dims), f)

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("XLA_FLAGS", None)  # workers size their own
        fleet_dir = os.path.join(run_dir, "fleet")
        cmd = [sys.executable, "-m", "distributed_pipeline_tpu.run.serve",
               "--checkpoint_path", run_dir, "--step", "2",
               "--replicas", "1", "--disagg", "1",
               "--fleet_dir", fleet_dir,
               "--decode_slots", str(decode_slots),
               "--page_size", str(page_size),
               "--max_prompt_len", str(max(8, prompt_len + 2)),
               "--max_new_tokens", str(gen_tokens),
               "--traffic", "bursty", "--rate_rps", str(rate_rps),
               "--burst_size", str(burst_size),
               "--synthetic_requests", str(requests),
               "--synthetic_prompt_len", str(prompt_len),
               "--hang_timeout_s", str(hang_timeout_s),
               "--fleet_deadline_s", str(max(30.0, timeout_s - 25.0))]
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, start_new_session=True,
            cwd=os.path.dirname(os.path.abspath(__file__)))
        try:
            out, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except OSError:
                pass
            proc.wait()
            return {"name": name,
                    "error": f"disagg run exceeded its {timeout_s:.0f}s "
                             f"timeout"}
        wall = time.perf_counter() - t0
        if proc.returncode != 0 or not out.strip():
            return {"name": name,
                    "error": f"disagg run failed (rc={proc.returncode}): "
                             f"{(err or out or '')[-300:]}"}
        res = json.loads(out.strip().splitlines()[-1])
        sgp = res.get("serving_goodput") or {}
        dgp = res.get("decode_goodput") or {}
        failures = []
        if res.get("dropped"):
            failures.append(f"{res['dropped']} admitted requests dropped")
        if res.get("completed") != requests:
            failures.append(f"{res.get('completed')}/{requests} completed")
        if not res.get("disagg"):
            failures.append("router did not run in disagg mode")
        if abs(sgp.get("accounted_frac", 0.0) - 1.0) > 0.05:
            failures.append(
                f"prefill ledger unaccounted "
                f"(frac={sgp.get('accounted_frac')})")
        if abs(dgp.get("accounted_frac", 0.0) - 1.0) > 0.05:
            failures.append(
                f"decode ledger unaccounted "
                f"(frac={dgp.get('accounted_frac')})")
        p50, p95 = res.get("ttft_p50_s"), res.get("ttft_p95_s")
        if p50 is None:
            failures.append("no TTFT percentiles")
        if failures:
            return {"name": name, "error": "; ".join(failures)[:500],
                    "leg_wall_s": round(wall, 1)}
        return {
            "name": name,
            "disagg": True,
            "requests": res["requests"],
            "completed": res["completed"],
            "dropped": res["dropped"],
            "ttft_p50_s": p50,
            "ttft_p95_s": p95,
            "decode_tokens_per_s": res.get("decode_tokens_per_s"),
            "prefill_accounted_frac": sgp.get("accounted_frac"),
            "decode_accounted_frac": dgp.get("accounted_frac"),
            "traffic": res.get("traffic"),
            "wall_s": res.get("wall_s"),
            "leg_wall_s": round(wall, 1),
        }

    def measure_prefetch_ab(name: str, *, family: str, size: str,
                            seq_len: int, batch: int, microbatch: int = 0,
                            window_steps: int = 4, rounds: int = 6,
                            prefetch_depth: int = 2, dispatch_lag: int = 1):
        """Paired interleaved prefetch A/B at the headline settings.

        Sequential OFF-then-ON legs measure the box as much as the code: on
        a shared/throttled host the steady-state rate drifts tens of
        percent over tens of seconds, so one pair of windows flips the
        delta's sign run to run (observed on this box: same config ranged
        24->38 steps/s across back-to-back reps). Here BOTH loops stay
        alive and short timed windows interleave between them, order
        alternating each round (ABBA), so slow drift hits the two arms
        equally. The delta comes from the POSITION-BALANCED TOTALS: on
        this box the second of two back-to-back windows runs ~25% slower
        regardless of arm (scheduler/cache position effect, measured), so
        per-round ratios are bimodal — but with ``rounds`` even, ABBA
        gives each arm first position exactly half the time and the
        position cost cancels in the summed times. Returns the
        prefetch-ON leg row (same schema as ``measure``) with the paired
        baseline attached as ``ab_*`` fields — the ``prefetch-ab-delta``
        row is derived from these, not from cross-leg numbers taken at
        different times."""
        if rounds % 2:
            rounds += 1  # even rounds: the ABBA position balance above
        dims = dict(vocab_size=8192) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        dataset = "synthetic-lm" if family == "gpt2" else "synthetic-seq2seq"

        def build(depth: int, lag: int) -> TrainLoop:
            wl = create_model_from_config(
                model_family=family, model_size=size, seq_len=seq_len,
                dtype=dtype, **dims)
            data = load_data_from_args(
                "train", batch_size=batch, dataset=dataset, seq_len=seq_len,
                vocab_size=dims["vocab_size"], seed=0, num_loader_proc=2)
            # Both arms sanitize: the transfer-guard context is entered per
            # step, so only a symmetric pair is a fair timing comparison.
            return TrainLoop(model=wl, data=data, batch_size=batch,
                             microbatch=microbatch or batch, lr=1e-4,
                             ema_rate="0.9999", learning_steps=0,
                             log_interval=10 ** 9, save_interval=10 ** 9,
                             mesh=make_mesh(dp=-1), checkpoint_dir="",
                             seed=0, sanitize=True, prefetch_depth=depth,
                             dispatch_lag=lag)

        warm = 7 if on_tpu else 2

        def warmup(loop: TrainLoop) -> float:
            t0 = time.perf_counter()
            m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            first_step_s = time.perf_counter() - t0
            for _ in range(warm):
                m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            loop.flush_metrics()
            loop.stalls.lap()  # gauges cover only the timed windows
            return first_step_s

        def window(loop: TrainLoop) -> float:
            t0 = time.perf_counter()
            for _ in range(window_steps):
                m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            return time.perf_counter() - t0

        # Two live TrainLoops double the device residency of measure()'s
        # single loop, and the scalar batch arg has no tuple ladder — so
        # an HBM OOM falls back by halving (keeping the PAIRED protocol)
        # instead of erroring out the one leg whose delta row the bench
        # exists to produce. The row's "batch" reports the size that ran.
        requested_batch = batch
        while True:
            try:
                # OFF arm is built and warmed FIRST, so the ON arm's
                # RecompileMonitor (installed at its construction) never
                # sees the OFF arm's compiles — the reported
                # recompile_count is the ON loop's own compiles plus any
                # steady-window retrace from either arm, which is exactly
                # the regression the gauge exists to catch. (Both
                # monitors hook the process-global 'jax' logger; they are
                # uninstalled in reverse install order below so their
                # saved jax_log_compiles flags nest correctly.)
                loop_off = build(0, 0)
                try:
                    warmup(loop_off)
                    loop_on = build(prefetch_depth, dispatch_lag)
                    try:
                        first_step_s = warmup(loop_on)
                        off_dts: list = []
                        on_dts: list = []
                        for r in range(rounds):
                            pair = ((loop_off, off_dts), (loop_on, on_dts))
                            for loop, dts in (pair[::-1] if r % 2 else pair):
                                dts.append(window(loop))
                        loop_on.flush_metrics()  # drain the lagged ring
                        stall = loop_on.stalls.lap()
                    finally:
                        recompiles = loop_on.stop_sanitizer()
                finally:
                    loop_off.stop_sanitizer()
            except (LegTimeout, BenchInterrupted):
                raise
            except Exception as e:
                msg = str(e)
                if (batch <= 1 or ("RESOURCE_EXHAUSTED" not in msg
                                   and "out of memory" not in msg.lower())):
                    raise
                print(f"# {name}: batch {batch} OOM with two live loops; "
                      f"retrying A/B at {batch // 2}", file=sys.stderr,
                      flush=True)
                batch //= 2
                microbatch = min(microbatch, batch) if microbatch else 0
                continue
            break
        n_steps = rounds * window_steps
        off_sps = n_steps / sum(off_dts)
        on_sps = n_steps / sum(on_dts)
        # identical step counts, so the totals ratio IS the rate ratio
        delta_pct = 100.0 * (sum(off_dts) / sum(on_dts) - 1.0)
        tps = (n_steps * batch * seq_len * jax.process_count()
               / sum(on_dts))
        fpt = transformer_train_flops_per_token(
            loop_on.n_params, loop_on.workload.num_layers,
            loop_on.workload.hidden_size, seq_len)
        row = {
            "name": name,
            "tokens_per_sec_per_chip": round(tps / jax.device_count(), 1),
            "steps_per_s": round(on_sps, 4),
            "mfu": round(mfu(tps, fpt), 4),
            "n_params": loop_on.n_params,
            "batch": batch, "microbatch": microbatch or batch,
            "seq_len": seq_len, "remat": False,
            "prefetch_depth": prefetch_depth, "dispatch_lag": dispatch_lag,
            "compile_s": round(loop_on.compile_time_s or 0.0, 3),
            "first_step_s": round(first_step_s, 3),
            "time_to_first_step_s": round(loop_on.time_to_first_step_s
                                          or 0.0, 3),
            "recompile_count": recompiles,
            "ab_method": "paired-interleaved",
            "ab_rounds": rounds, "ab_window_steps": window_steps,
            "ab_off_steps_per_s": round(off_sps, 4),
            "ab_delta_pct": round(delta_pct, 2),
        }
        if batch != requested_batch:
            row["ab_batch_fallback"] = True
        row.update({k: round(v, 6) for k, v in stall.items()})
        fp = loop_on.footprint()
        row.update({k: fp[k] for k in (
            "params_bytes", "opt_state_bytes",
            "opt_state_bytes_per_replica", "peak_live_bytes")})
        return row

    def measure_trace_ab(name: str, *, family: str, size: str,
                         seq_len: int, batch: int, microbatch: int = 0,
                         window_steps: int = 4, rounds: int = 6):
        """Trace-overhead guard (ISSUE 12): paired interleaved A/B at the
        headline settings between span tracing ON (obs/: one step span +
        flushed JSONL append per step, booked into a real run dir) and
        OFF (the NULL-tracer zero-cost path). Same ABBA protocol as
        measure_prefetch_ab — both loops stay alive, short timed windows
        interleave with alternating order, the delta comes from the
        position-balanced totals — because the contract is a NOISE-BAND
        claim (tracing-on within +-3% of off on this box), and sequential
        legs cannot distinguish a 1% instrumentation cost from host
        drift. The ``trace-ab-delta`` row derives from this leg's paired
        fields; the ON arm's trace shard is also sanity-checked non-empty
        (a silently disarmed tracer would 'prove' a zero overhead no one
        is paying)."""
        import shutil

        if rounds % 2:
            rounds += 1  # even rounds: ABBA position balance
        dims = dict(vocab_size=8192) if on_tpu else dict(
            hidden_size=64, num_layers=2, num_heads=4, vocab_size=256)
        dataset = ("synthetic-lm" if family == "gpt2"
                   else "synthetic-seq2seq")
        trace_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "trace_ab"))
        shutil.rmtree(trace_dir, ignore_errors=True)

        def build(tag: str, trace: bool) -> TrainLoop:
            # both arms get a (fresh) run dir so construction is
            # symmetric; only the tracer differs. trace is passed as an
            # explicit bool: False FORCES the control arm off even when
            # DPT_TRACE is exported (the env fallback would otherwise
            # trace both arms and "prove" a zero overhead nobody pays)
            run_dir = os.path.join(trace_dir, tag)
            os.makedirs(run_dir, exist_ok=True)
            wl = create_model_from_config(
                model_family=family, model_size=size, seq_len=seq_len,
                dtype=dtype, **dims)
            data = load_data_from_args(
                "train", batch_size=batch, dataset=dataset,
                seq_len=seq_len, vocab_size=dims["vocab_size"], seed=0,
                num_loader_proc=2)
            return TrainLoop(model=wl, data=data, batch_size=batch,
                             microbatch=microbatch or batch, lr=1e-4,
                             ema_rate="0.9999", learning_steps=0,
                             log_interval=10 ** 9, save_interval=10 ** 9,
                             mesh=make_mesh(dp=-1), checkpoint_dir=run_dir,
                             seed=0, sanitize=True, trace=trace)

        warm = 7 if on_tpu else 2

        def warmup(loop: TrainLoop) -> None:
            m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            for _ in range(warm):
                m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))

        def window(loop: TrainLoop) -> float:
            t0 = time.perf_counter()
            for _ in range(window_steps):
                m = loop.run_step(loop.next_batch())
            float(jax.device_get(m["loss"]))
            return time.perf_counter() - t0

        from distributed_pipeline_tpu.obs.trace import trace_path

        # Two live TrainLoops double the device residency (the same
        # situation measure_prefetch_ab handles): an HBM OOM halves the
        # batch and retries the PAIRED protocol instead of erroring out
        # the overhead-guard leg. The row's "batch" reports what ran.
        requested_batch = batch
        while True:
            try:
                loop_off = build("off", trace=False)
                try:
                    assert not loop_off.tracer.enabled  # a traced OFF
                    # arm would invalidate the whole comparison
                    warmup(loop_off)
                    loop_on = build("on", trace=True)
                    try:
                        warmup(loop_on)
                        off_dts: list = []
                        on_dts: list = []
                        for r in range(rounds):
                            pair = ((loop_off, off_dts), (loop_on, on_dts))
                            for loop, dts in (pair[::-1] if r % 2
                                              else pair):
                                dts.append(window(loop))
                        traced_events = 0
                        shard = trace_path(os.path.join(trace_dir, "on"),
                                           0)
                        if os.path.exists(shard):
                            with open(shard) as f:
                                traced_events = sum(
                                    1 for line in f if line.strip())
                        loop_on.tracer.close()
                    finally:
                        loop_on.stop_sanitizer()
                finally:
                    loop_off.stop_sanitizer()
            except (LegTimeout, BenchInterrupted):
                raise
            except Exception as e:
                msg = str(e)
                if (batch <= 1 or ("RESOURCE_EXHAUSTED" not in msg
                                   and "out of memory"
                                   not in msg.lower())):
                    raise
                print(f"# {name}: batch {batch} OOM with two live loops; "
                      f"retrying A/B at {batch // 2}", file=sys.stderr,
                      flush=True)
                batch //= 2
                microbatch = min(microbatch, batch) if microbatch else 0
                shutil.rmtree(trace_dir, ignore_errors=True)
                continue
            break
        n_steps = rounds * window_steps
        off_sps = n_steps / sum(off_dts)
        on_sps = n_steps / sum(on_dts)
        delta_pct = 100.0 * (sum(off_dts) / sum(on_dts) - 1.0)
        if not traced_events:
            return {"name": name,
                    "error": "trace arm wrote no events — the A/B "
                             "measured nothing (tracer disarmed?)"}
        fallback = {"ab_batch_fallback": True} \
            if batch != requested_batch else {}
        tps = (n_steps * batch * seq_len * jax.process_count()
               / sum(on_dts))
        fpt = transformer_train_flops_per_token(
            loop_on.n_params, loop_on.workload.num_layers,
            loop_on.workload.hidden_size, seq_len)
        return {
            "name": name,
            "tokens_per_sec_per_chip": round(tps / jax.device_count(), 1),
            "steps_per_s": round(on_sps, 4),
            "mfu": round(mfu(tps, fpt), 4),
            "n_params": loop_on.n_params,
            "batch": batch, "microbatch": microbatch or batch,
            "seq_len": seq_len,
            "trace_events": traced_events,
            "compile_s": round(loop_on.compile_time_s or 0.0, 3),
            "ab_method": "paired-interleaved",
            "ab_rounds": rounds, "ab_window_steps": window_steps,
            "ab_off_steps_per_s": round(off_sps, 4),
            "ab_delta_pct": round(delta_pct, 2),
            **fallback,
        }

    def measure_zero1_ab(name: str, *, batch: int, microbatch: int,
                         seq_len: int, window_steps: int, rounds: int,
                         size: str = "base", cpu_hidden: int = 256,
                         cpu_layers: int = 2, timeout_s: float = 200.0):
        """ZeRO-1 A/B leg (ISSUE 9): paired interleaved shard_optimizer
        ON/OFF at the headline shape on a >= 2-way data axis, run in a
        CHILD PROCESS (run/zero1_ab.py) so the CPU smoke box — one real
        device — still gets a dp=2 mesh via forced host devices; on TPU
        the child sees the real chips. The row's acceptance numbers:
        ``opt_bytes_replica_ratio`` ~ dp (per-replica optimizer+EMA bytes
        drop by the data-parallel factor) while ``ab_delta_pct`` stays
        inside the box noise band (steps/s parity — ZeRO-1 trades a
        per-step update all-gather for dp x less weight-update memory)
        and ``steady_recompile_count`` == 0 (pinned out_shardings: the
        sharded layout compiles exactly once).

        ``size`` selects the preset — the xl leg (ISSUE 10 satellite)
        runs the SAME protocol at the xl shape the ZeRO-1 headroom
        exists for; a child that dies (HBM OOM at xl with two live
        loops) comes back as an error row, never an abort.

        Spawn/env-pinning/timeout-folding is the tuner's shared
        child-measurement scaffold (tune/measure.py — one owner, ISSUE
        13 satellite); only the ZeRO flag set and CPU dims live here."""
        from distributed_pipeline_tpu.tune import measure as tune_measure

        args = ["--family", "diffuseq", "--size", size,
                "--batch", str(batch), "--microbatch", str(microbatch),
                "--seq_len", str(seq_len), "--dtype", dtype,
                "--window_steps", str(window_steps),
                "--rounds", str(rounds)]
        if not on_tpu:
            # Wider than the usual CPU smoke dims (hidden 256 vs 64): the
            # per-step weight-update all-gather is a fixed ~per-leaf op
            # cost on CPU, so the step must carry enough matmul for the
            # parity contract to be measurable (at hidden 64 the op
            # overhead alone reads as -15%; at 256 the delta sits inside
            # the +-3% noise band — measured on this box). The xl leg
            # scales these up so its CPU smoke row still exercises a
            # bigger-model shape than the base leg.
            args += ["--hidden", str(cpu_hidden),
                     "--layers", str(cpu_layers), "--heads", "4",
                     "--vocab", "256"]
        row = tune_measure.run_child(
            "distributed_pipeline_tpu.run.zero1_ab", args,
            env=tune_measure.child_env(None if on_tpu else 2),
            timeout_s=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            tag="zero1 A/B child")
        row["name"] = name
        return row

    def measure_tune(name: str, *, budget_s: float = 150.0,
                     timeout_s: float = 215.0, screen_steps: int = 5,
                     noise_band_pct: float = 3.0):
        """Auto-tuner acceptance leg (ISSUE 13): a SCREEN-ONLY budgeted
        layout search for the headline family on the forced-host dp=2
        CPU mesh — always the CPU tuner stack, like every robustness
        leg: it measures the control loop, not the chip. Acceptance:
        the tuner must REPRODUCE OR BEAT the hand-tuned family table's
        steps/s (the baseline candidate, measured first) within the
        box's +-3% noise band, account for every enumerated candidate
        (rejected + measured + pruned + skipped == enumerated), and the
        winner's steady recompile count must be 0."""
        import shutil

        from distributed_pipeline_tpu.tune import measure as tune_measure

        out_dir = os.path.abspath(
            os.path.join("model_checkpoints", "bench", "tune_run"))
        shutil.rmtree(out_dir, ignore_errors=True)
        args = ["--family", "diffuseq", "--n_devices", "2",
                "--screen_only", "true", "--budget_s", str(budget_s),
                "--batch_size", "8", "--microbatch", "8",
                "--seq_len", "128", "--vocab_size", "256",
                "--hidden_size", "64", "--num_layers", "2",
                "--num_heads", "4", "--dtype", "float32",
                "--screen_steps", str(screen_steps),
                "--child_timeout_s", "90",
                "--out_dir", out_dir]
        row = tune_measure.run_child(
            "distributed_pipeline_tpu.run.tune", args,
            # the tune PARENT runs on 2 forced CPU host devices too (its
            # candidate validation is arithmetic; children re-force)
            env=tune_measure.child_env(2), timeout_s=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            tag="tune leg")
        if "error" in row:
            return {"name": name, "error": row["error"]}
        fam = (row.get("families") or {}).get("diffuseq") or {}
        counts = fam.get("counts") or {}
        winner = fam.get("winner") or {}
        base_sps = fam.get("baseline_steps_per_s")
        win_sps = winner.get("steps_per_s")
        failures = []
        if fam.get("accounted") != counts.get("enumerated"):
            failures.append(
                f"trial accounting broken: {fam.get('accounted')} "
                f"accounted != {counts.get('enumerated')} enumerated")
        if not base_sps:
            failures.append("hand-tuned baseline candidate not measured")
        if not win_sps:
            failures.append("no winner measured")
        ratio = (win_sps / base_sps) if base_sps and win_sps else 0.0
        if base_sps and win_sps and \
                ratio < 1.0 - noise_band_pct / 100.0:
            failures.append(
                f"tuner lost to the hand-tuned table: winner "
                f"{win_sps} vs baseline {base_sps} steps/s "
                f"({100 * (ratio - 1):+.1f}%, band +-{noise_band_pct}%)")
        if winner and winner.get("steady_recompile_count") not in (0, None):
            failures.append(
                f"winner recompiled in steady state "
                f"({winner.get('steady_recompile_count')})")
        if failures:
            return {"name": name, "error": "; ".join(failures)[:500]}
        return {
            "name": name,
            "winner": winner.get("cid"),
            "winner_mesh": winner.get("mesh"),
            "winner_zero1": winner.get("shard_optimizer"),
            "winner_steps_per_s": win_sps,
            "baseline_steps_per_s": base_sps,
            "winner_vs_baseline": round(ratio, 4),
            "noise_band_pct": noise_band_pct,
            "enumerated": counts.get("enumerated"),
            "measured": counts.get("measured"),
            "rejected": counts.get("rejected"),
            "pruned": counts.get("pruned"),
            "skipped": counts.get("skipped"),
            "steady_recompile_count": winner.get("steady_recompile_count"),
            "tune_elapsed_s": row.get("elapsed_s"),
            "n_devices": row.get("n_devices"),
        }

    # Per-chip batch sizes are the measured MFU sweet spots on v5e (base:
    # 64/128/256/512 sweep in r2; large/gpt2 sized to fit one chip's HBM
    # with the single-EMA bench loop); tiny on CPU so smoke runs finish.
    bsz = (lambda b: b if on_tpu else 4)
    # Legs are LAZY (name, thunk) pairs so the budget guard can drop a leg
    # without paying its compile, ordered headline-first so a truncated run
    # always contains the north star.
    legs = [
        # Headline: BASELINE config 2/3 shape with the reference's DEFAULT
        # microbatch of 64 (ref config/train.py:11-12) — which the sweep
        # (16/32/64/128 at batch 256) also measures as the v5e throughput
        # optimum (76% MFU vs 68% unaccumulated: the scan's smaller
        # working set schedules better).
        ("diffuseq-base-seq128", functools.partial(
            measure, "diffuseq-base-seq128", family="diffuseq", size="base",
            seq_len=128, batch=bsz(256), microbatch=bsz(256) // 4 or 1,
            steady_steps=30 if on_tpu else 12)),
        # Steady-state A/B (ISSUE 5): the EXACT headline settings with
        # device-side double-buffered prefetch + async lagged-metrics
        # dispatch ON, measured as PAIRED INTERLEAVED windows against a
        # live prefetch-OFF twin (see measure_prefetch_ab: sequential legs
        # confound the delta with host drift). On TPU the batch transfer
        # overlaps the running step (the real win); on CPU (synchronous
        # backend) the contract is "no slower". The prefetch-ab-delta row
        # below reports the paired delta.
        ("diffuseq-base-seq128-prefetch", functools.partial(
            measure_prefetch_ab, "diffuseq-base-seq128-prefetch",
            family="diffuseq", size="base", seq_len=128, batch=bsz(256),
            microbatch=bsz(256) // 4 or 1,
            window_steps=10 if on_tpu else 4,
            rounds=6 if on_tpu else 32,
            prefetch_depth=int(os.environ.get("BENCH_PREFETCH_DEPTH", "2")),
            dispatch_lag=int(os.environ.get("BENCH_DISPATCH_LAG", "1")))),
        # ZeRO-1 A/B (ISSUE 9): the headline shape with cross-replica
        # optimizer/EMA sharding ON vs OFF, paired-interleaved in a child
        # process on a >= 2-way data axis (forced 2 host devices on the
        # CPU box; the real chips on TPU). Acceptance: per-replica
        # optimizer bytes / dp at steps/s parity, steady recompiles 0.
        ("diffuseq-base-seq128-zero1", functools.partial(
            measure_zero1_ab, "diffuseq-base-seq128-zero1",
            # CPU smoke: batch 8 unaccumulated (the child's dp=2 mesh
            # needs the microbatch divisible by 2, and the wider CPU
            # model wants the larger per-step compute — see
            # measure_zero1_ab's dims note)
            batch=256 if on_tpu else 8,
            microbatch=64 if on_tpu else 8, seq_len=128,
            window_steps=10 if on_tpu else 6,
            rounds=6 if on_tpu else 8)),
        # Fused optimizer+EMA update leg (ISSUE 18): the headline shape
        # with --fused_update (ops/fused_update.py one-pass kernel;
        # interpreter mode on CPU), landing the kernel's exact bytes/step
        # next to the staged optax chain's cost-analysis bytes
        # (acceptance: strictly below, losses bit-identical — the parity
        # suite owns the loss check, this row owns the traffic claim).
        ("diffuseq-base-seq128-fusedupd", functools.partial(
            measure, "diffuseq-base-seq128-fusedupd", family="diffuseq",
            size="base", seq_len=128, batch=bsz(256),
            microbatch=bsz(256) // 4 or 1,
            steady_steps=30 if on_tpu else 6, fused_update=True)),
        # Trace-overhead guard (ISSUE 12): span tracing ON vs OFF at the
        # headline settings, paired-interleaved like the other A/B twins.
        # The contract is a noise-band claim — tracing must cost within
        # +-3% on the headline leg, or it cannot be left armed on real
        # runs. The trace-ab-delta row below derives from this leg.
        ("diffuseq-base-seq128-trace", functools.partial(
            measure_trace_ab, "diffuseq-base-seq128-trace",
            family="diffuseq", size="base", seq_len=128, batch=bsz(256),
            microbatch=bsz(256) // 4 or 1,
            window_steps=10 if on_tpu else 4,
            rounds=6 if on_tpu else 32)),
        # Serving decode legs (ISSUE 7): continuous-batching decode
        # tokens/s/chip at 1 / 8 / 64 slots plus time-to-first-token,
        # through the prefill/decode AOT split + paged KV cache
        # (serving/). Early in the order so a truncated run still lands
        # the serving acceptance rows; the one-shot batch-1 twin right
        # after anchors the serve-vs-oneshot ratio on the same box.
        ("gpt2-serve-decode-b1", functools.partial(
            measure_serve, "gpt2-serve-decode-b1", slots=1,
            num_requests=5 if on_tpu else 4,
            gen_tokens=128 if on_tpu else 24,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 64)),
        ("gpt2-serve-decode-b8", functools.partial(
            measure_serve, "gpt2-serve-decode-b8", slots=8,
            num_requests=25 if on_tpu else 25,
            gen_tokens=128 if on_tpu else 24,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 64)),
        # the b64 leg ramps 64 slots full through prefill_batch-16
        # admissions, then holds occupancy across the request stream —
        # the acceptance leg for the >= 3x serve-vs-oneshot ratio
        ("gpt2-serve-decode-b64", functools.partial(
            measure_serve, "gpt2-serve-decode-b64", slots=64,
            num_requests=193 if on_tpu else 193,
            gen_tokens=128 if on_tpu else 24,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 64, prefill_batch=16)),
        # Flash-decode kernel leg (ISSUE 18): decode_impl=pallas through
        # the same continuous-batching protocol, token-identity checked
        # against an xla twin run, with the kernel's schedule-exact HBM
        # bytes/token landed next to the gather path's cost-analysis
        # bytes (acceptance: strictly below).
        ("gpt2-serve-decode-kernel", functools.partial(
            measure_serve_decode_kernel, "gpt2-serve-decode-kernel",
            slots=8, num_requests=25 if on_tpu else 6,
            gen_tokens=128 if on_tpu else 12,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 64)),
        # Speculative-decoding leg (ISSUE 20): spec_tokens=K with the
        # zero-flop ngram draft vs a decode_span=1 twin on the same
        # prompts — accepted-tokens/s ratio from dispatch amortization,
        # greedy token identity checked in-leg.
        ("gpt2-serve-spec-decode", functools.partial(
            measure_serve_spec_decode, "gpt2-serve-spec-decode",
            slots=4, num_requests=25 if on_tpu else 6,
            gen_tokens=128 if on_tpu else 160,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 256,
            spec_tokens=3 if on_tpu else 2)),
        # int8 paged-KV leg (ISSUE 20): kv_quant=int8 vs fp twin at the
        # same geometry — pool-bytes ratio <= 0.55x from the engines'
        # buffer census, kernel-schedule HBM bytes ratio, and 2x slots
        # served inside the fp pool budget.
        ("gpt2-serve-decode-int8", functools.partial(
            measure_serve_decode_int8, "gpt2-serve-decode-int8",
            slots=4, num_requests=25 if on_tpu else 6,
            gen_tokens=128 if on_tpu else 12,
            prompt_len=128 if on_tpu else 8,
            page_size=16 if on_tpu else 4,
            seq_len=1024 if on_tpu else 64)),
        ("gpt2-base-decode-oneshot-b1", functools.partial(
            measure_decode, "gpt2-base-decode-oneshot-b1",
            gen_tokens=128 if on_tpu else 24,
            batch=1, seq_len=1024 if on_tpu else 64)),
        # Chaos/goodput leg (ISSUE 8): headline-named because it proves
        # the headline WORKFLOW (elastic launcher + auto-resume + warm
        # compile cache) survives two injected kills — one mid-step, one
        # mid-checkpoint-save — with goodput >= 0.7 and zero steady-state
        # recompiles on resumed attempts. Always the CPU smoke shape: the
        # leg measures the recovery stack, not the chip. Step counts are
        # sized so useful step time dominates the ~3 attempts' fixed
        # startup+compile overhead on this box.
        # kill_step is deliberately OFF the save cadence: the 100 steps
        # since the last checkpoint are lost and re-run after resume —
        # the recompute_s share of the breakdown.
        ("diffuseq-base-seq128-chaos", functools.partial(
            measure_chaos, "diffuseq-base-seq128-chaos",
            steps=4000, save_interval=250, batch=16,
            kill_step=1600, crash_save_step=2750)),
        # Elastic + hang-watchdog leg (ISSUE 10): the failures the chaos
        # leg cannot model — a worker that WEDGES without exiting (the
        # stall_step fault; the --hang_timeout_s watchdog must detect
        # the frozen beacons and kill the ring) and a SHRUNK restart
        # (DPT_FORCE_DEVICES_PER_PROC drops the ring dp=2 -> dp=1, so
        # the resume reshards state onto the smaller mesh). Acceptance:
        # completes with goodput >= 0.6, accounted_frac == 1.0 including
        # the new hang category, watchdog kill within timeout + grace,
        # steady recompiles 0 on resumed attempts.
        ("diffuseq-base-seq128-elastic", functools.partial(
            measure_elastic, "diffuseq-base-seq128-elastic",
            steps=3000, save_interval=250, stall_step_at=1400,
            hang_timeout_s=2.0, batch=16)),
        # Auto-tuner leg (ISSUE 13): screen-only budgeted layout search
        # on the forced-host dp=2 CPU mesh — the tuner must reproduce or
        # beat the hand-tuned family table within the +-3% noise band,
        # journal every trial (accounting closed), and land a winner
        # with steady recompiles 0. Always the CPU tuner stack: the leg
        # measures the control loop, not the chip.
        ("diffuseq-base-seq128-tune", functools.partial(
            measure_tune, "diffuseq-base-seq128-tune")),
        # Serving-fleet resilience leg (ISSUE 11): 3 replicas under
        # sustained Poisson load, one kill_replica mid-request + one
        # checkpoint hot-swap; acceptance is p50/p95 TTFT SLOs under
        # load, zero dropped admitted requests, and serving
        # accounted_frac 1.0. Placed AFTER the headline glob so an
        # OOM/timeout degrades to an error row and can never block the
        # headline. (Replica workers are always CPU dev rings — this
        # leg measures the resilience stack, not the chip.)
        ("gpt2-serve-fleet-chaos", functools.partial(
            measure_serve_fleet, "gpt2-serve-fleet-chaos",
            replicas=3, requests=16, rate_rps=2.0, gen_tokens=10,
            kill_after=2, swap_after=5)),
        # MPMD pipeline leg (ISSUE 16): host-driven 1F1B across two
        # single-process stage rings with activations/grads over the
        # StageLink host relay. Acceptance: finite losses with zero
        # rewinds, the per-stage fold accounting every stage-second
        # (accounted_frac 1.0, link_wait category present), steady
        # recompiles 0. Always the CPU substrate shape — this measures
        # the MPMD runtime, not the chip.
        ("diffuseq-base-seq128-mpmd-pipe", functools.partial(
            measure_mpmd_pipe, "diffuseq-base-seq128-mpmd-pipe",
            steps=3, n_stages=2, n_microbatches=4, batch=8,
            seq_len=128)),
        # Disaggregated serving leg (ISSUE 16): prefill tier streams
        # paged-KV frames over StageLink to a decode tier on a separate
        # worker, bursty arrivals; TTFT reads against the colocated
        # gpt2-serve-decode-b8 row. Acceptance: all requests complete,
        # zero drops, BOTH tiers' ledgers hold accounted_frac 1.0.
        ("gpt2-serve-disagg", functools.partial(
            measure_serve_disagg, "gpt2-serve-disagg",
            requests=8, gen_tokens=6, rate_rps=6.0, burst_size=4)),
        # Autoscaling fleet leg (ISSUE 17): seeded diurnal traffic over
        # a shared-prefix workload, three fleet runs on one checkpoint —
        # prefix-affinity A/B arm, static-max baseline, and --replicas 1
        # under the SLO autoscaler. Acceptance: >= 1 journaled scale-up
        # AND drain-based scale-down, zero drops, p95 TTFT inside the
        # documented CPU SLO, the autoscaled replica-seconds bill below
        # static-max, affinity's fleet-wide prefix hit rate above
        # least-loaded's, and every ledger closing at accounted_frac
        # 1.0 with paid_idle booked.
        ("gpt2-serve-autoscale", functools.partial(
            measure_serve_autoscale, "gpt2-serve-autoscale",
            requests=20, rate_rps=0.8, diurnal_period_s=20.0,
            max_replicas=2, gen_tokens=8)),
        # no-accumulation variant (pure config-2 semantics)
        ("diffuseq-base-seq128-noaccum", functools.partial(
            measure, "diffuseq-base-seq128-noaccum", family="diffuseq",
            size="base", seq_len=128, batch=bsz(256))),
        # config 3 shape: large model, long sequence, +/- remat. Small
        # microbatches are the big lever at this scale (46% MFU at
        # batch=microbatch=32 -> 69.7% at batch 128/microbatch 4: the tiny
        # per-chunk working set keeps everything near the MXU while the
        # scan amortizes the optimizer/EMA); at these chunk sizes XLA's
        # dense attention beats the flash kernel, which "auto" already
        # picks below 1k context.
        ("diffuseq-large-seq512", functools.partial(
            measure, "diffuseq-large-seq512", family="diffuseq",
            size="large", seq_len=512, batch=(bsz(128), bsz(32), bsz(8)),
            microbatch=bsz(4))),
        ("diffuseq-large-seq512-remat", functools.partial(
            measure, "diffuseq-large-seq512-remat", family="diffuseq",
            size="large", seq_len=512, batch=(bsz(128), bsz(32), bsz(8)),
            microbatch=bsz(8), remat=True)),
        # config 4: the causal-LM path (different xent/attention profile);
        # microbatch 32 is its measured optimum (74.8% vs 66.7% at 128).
        ("gpt2-medium-seq128", functools.partial(
            measure, "gpt2-medium-seq128", family="gpt2", size="medium",
            seq_len=128, batch=(bsz(256), bsz(64), bsz(32)),
            microbatch=bsz(32))),
        # Long context (exceeds the BASELINE shapes): the Pallas flash
        # kernel path — "auto" picks it on TPU from 1k context — at 4k,
        # where the dense [L, L] logits would dominate HBM traffic
        # (measured 1.67x the XLA path at this shape on v5e). The CPU
        # smoke run shrinks the sequence: a 4k dense attention on one CPU
        # core takes minutes and measures nothing.
        # batch/microbatch are the r4 sweep optimum (saturates from b=32;
        # microbatch 2 beats 1 and 4 at both lengths); 1024x1024 kernel
        # blocks + the diagonal-only causal masking lifted this shape
        # 41.5% -> 49.6% MFU (PARITY.md long-context section).
        ("gpt2-base-seq4096-flash", functools.partial(
            measure, "gpt2-base-seq4096-flash", family="gpt2", size="base",
            seq_len=4096 if on_tpu else 256,
            batch=(bsz(64), bsz(16), bsz(4)), microbatch=bsz(2))),
        # Long-context curve extension: 8k context through the same flash
        # path (quadratic attention share doubles vs 4k).
        ("gpt2-base-seq8192-flash", functools.partial(
            measure, "gpt2-base-seq8192-flash", family="gpt2", size="base",
            seq_len=8192 if on_tpu else 256,
            batch=(bsz(32), bsz(8), bsz(2)), microbatch=bsz(2))),
        # MoE: 8 experts top-2 in every 2nd block — measures the one-hot
        # dispatch/combine einsum cost on real hardware (MFU against
        # ACTIVE params: only top_k experts run per token).
        ("diffuseq-base-seq128-moe8", functools.partial(
            measure, "diffuseq-base-seq128-moe8", family="diffuseq",
            size="base", seq_len=128, batch=(bsz(256), bsz(64)),
            microbatch=bsz(256) // 4 or 1, moe_experts=8, moe_top_k=2)),
        # Same MoE at capacity_factor 1.0: zero padding slots (E*C == K*L).
        # artifacts/moe_gap.py decomposes the moe8 MFU gap — at cf 1.25 the
        # expert GEMMs pay ~2x the +25% slot flops (non-power-of-two row
        # tiling), at cf 1.0 they run at dense efficiency; the knob
        # (--moe_capacity_factor) trades overflow drops for throughput.
        ("diffuseq-base-seq128-moe8-cf1", functools.partial(
            measure, "diffuseq-base-seq128-moe8-cf1", family="diffuseq",
            size="base", seq_len=128, batch=(bsz(256), bsz(64)),
            microbatch=bsz(256) // 4 or 1, moe_experts=8, moe_top_k=2,
            moe_capacity_factor=1.0)),
        # scan_layers: the stacked-weights layer scan (one traced block) —
        # quantifies the compile-time-vs-MFU tradeoff PARITY.md documents,
        # in the driver signal.
        ("diffuseq-base-seq128-scan", functools.partial(
            measure, "diffuseq-base-seq128-scan", family="diffuseq",
            size="base", seq_len=128, batch=bsz(256),
            microbatch=bsz(256) // 4 or 1, scan_layers=True)),
        # KV-cache decode throughput (generation, not training) at two
        # batch sizes — the pair anchors the batch-scaling curve (decode
        # is latency-bound per step, so tokens/s should scale near-
        # linearly with batch until the weight-streaming bandwidth wall).
        ("gpt2-base-decode128", functools.partial(
            measure_decode, "gpt2-base-decode128",
            gen_tokens=128 if on_tpu else 8,
            batch=bsz(64), seq_len=1024 if on_tpu else 64)),
        ("gpt2-base-decode128-b8", functools.partial(
            measure_decode, "gpt2-base-decode128-b8",
            gen_tokens=128 if on_tpu else 8,
            batch=8 if on_tpu else 2,
            seq_len=1024 if on_tpu else 64)),
        # First xl-preset leg (ISSUE 10 satellite, CHANGES r11 note):
        # ZeRO-1's per-replica headroom is what makes the xl shape fit a
        # chip at all, so it runs the zero1 A/B protocol at model_size
        # xl. Last in the order and budget-capped like every leg — an
        # OOM or overrun becomes an error row, never a blocked headline.
        # (CPU smoke scales the child dims up vs the base leg so the row
        # still exercises a bigger shape.)
        ("diffuseq-xl-seq128-zero1", functools.partial(
            measure_zero1_ab, "diffuseq-xl-seq128-zero1", size="xl",
            batch=64 if on_tpu else 8,
            microbatch=16 if on_tpu else 8, seq_len=128,
            window_steps=8 if on_tpu else 4,
            rounds=4 if on_tpu else 6,
            cpu_hidden=320, cpu_layers=3, timeout_s=220.0)),
    ]

    only = os.environ.get("BENCH_ONLY", "")
    if only:  # iteration filter: BENCH_ONLY=<exact name | *glob*>
        legs = select_legs(legs, only)

    # Fresh artifact per run (a crash mid-run leaves the completed prefix).
    if artifact_path:
        open(artifact_path, "w").close()

    # Bench HISTORY (ISSUE 14): unlike the per-run artifact, this file is
    # APPEND-ONLY across runs — every leg row lands here stamped with this
    # run's id, so the empty bench trajectory becomes a watched time
    # series (obs/regress.py compares the newest run against a trailing
    # baseline window). BENCH_HISTORY= (empty) disables.
    history_path = os.environ.get("BENCH_HISTORY", "bench_history.jsonl")
    run_id = f"{time.strftime('%Y%m%d-%H%M%S')}.{os.getpid()}"

    configs = []

    def emit(row: dict) -> None:
        """Record one leg NOW: final-JSON list + JSONL artifact + stderr
        + history. A later timeout/crash can only lose legs that never
        finished."""
        configs.append(row)
        if artifact_path:
            with open(artifact_path, "a") as f:
                f.write(json.dumps(row) + "\n")
        if history_path:
            try:  # history is telemetry: a read-only disk must not
                with open(history_path, "a") as f:  # sink the bench
                    f.write(json.dumps({**row, "run_id": run_id,
                                        "t": time.time()}) + "\n")
                    f.flush()
            except OSError as e:
                print(f"# bench history append failed: {e}",
                      file=sys.stderr, flush=True)
        print(f"# leg {json.dumps(row)} [t+"
              f"{time.perf_counter() - t_bench0:.0f}s]", file=sys.stderr,
              flush=True)

    # ------------------------------------------------------- hang hardening
    # The final JSON must print NO MATTER WHAT happens inside a leg (the
    # BENCH_r05 regression: the whole run wedged inside leg 1, rc=124,
    # parsed: null). Three nets, outermost last:
    #   per-leg SIGALRM cap -> SIGTERM catch -> native-hang watchdog.
    printed = threading.Lock()

    def final_payload() -> str:
        # a TRAIN row: serving rows also carry "mfu" now (the decode
        # roofline attribution), so the headline pick keys on the
        # train-schema column it actually reports
        if only:
            head = next((c for c in configs
                         if "tokens_per_sec_per_chip" in c), None)
        else:
            head = (configs[0] if configs
                    and "tokens_per_sec_per_chip" in configs[0] else None)
        if only and head is not None:
            metric = (f"tokens/sec/chip ({head['name']} [BENCH_ONLY={only}], "
                      f"{jax.devices()[0].device_kind})")
        else:
            metric = ("tokens/sec/chip (DiffuSeq-base seq128 train, "
                      f"{jax.devices()[0].device_kind})")
        return json.dumps({
            "metric": metric,
            "value": head["tokens_per_sec_per_chip"] if head else None,
            "unit": "tokens/s/chip",
            "vs_baseline": round(head["mfu"] / 0.40, 4) if head else None,
            "mfu": head["mfu"] if head else None,
            "n_params": head["n_params"] if head else None,
            "n_devices": jax.device_count(),
            "budget_s": budget_s,
            "elapsed_s": round(time.perf_counter() - t_bench0, 1),
            "compilation_cache": cache_dir,
            "configs": configs,
        })

    def print_final_once() -> None:
        if printed.acquire(blocking=False):
            print(final_payload(), flush=True)

    # The headline leg is EXEMPT from the budget skip (a bench run that
    # reports nothing is strictly worse than one that overruns a little),
    # so its hard cap gets a 120s floor — a 1s test budget must not kill
    # the one leg whose numbers are the contract. It is still capped: the
    # r5 wedge (a leg that never returns) cannot eat the driver's timeout.
    headline_cap_s = max(budget_s * 0.8, 120.0)

    # Anchored HERE — after jax import / distributed init / cache setup —
    # not at t_bench0: the per-leg SIGALRM caps are leg-start-relative, so
    # a slow startup (minutes on a TPU pod) must not let the watchdog
    # shoot a headline leg that is still inside its own hard cap.
    t_legs0 = time.perf_counter()

    def _watchdog() -> None:
        # Terminal backstop: a native call that never returns to the
        # interpreter (stuck XLA compile, wedged remote chip) defeats both
        # signal handlers — after the longest legitimate wall clock plus
        # 60s grace, print the completed rows and exit hard. The thread is
        # a daemon: a normal finish just abandons it.
        deadline = t_legs0 + max(budget_s, headline_cap_s) + 60.0
        while time.perf_counter() < deadline:
            time.sleep(1.0)
        try:
            print("# bench watchdog: wall clock exceeded budget inside a "
                  "leg; emitting final JSON with completed rows",
                  file=sys.stderr, flush=True)
            print_final_once()
        finally:
            # exit even if the prints raise (closed pipe): a wedged
            # process that lingers past the backstop defeats its purpose
            os._exit(3)

    threading.Thread(target=_watchdog, daemon=True).start()

    def _on_term(signum, frame):
        raise BenchInterrupted()

    prev_term = signal.signal(signal.SIGTERM, _on_term)

    try:
        try:
            for i, (name, thunk) in enumerate(legs):
                elapsed = time.perf_counter() - t_bench0
                if i > 0 and elapsed > budget_s:
                    emit({"name": name, "skipped": "budget"})
                    continue
                cap = (headline_cap_s if i == 0
                       else min(leg_budget_s, budget_s - elapsed))
                try:
                    emit(_run_capped(thunk, cap))
                except BenchInterrupted:
                    raise
                except Exception as e:
                    # One leg must not sink the others (or the final JSON
                    # line).
                    emit({"name": name,
                          "error": f"{type(e).__name__}: {e}"[:500]})
        except BenchInterrupted:
            done = {c.get("name") for c in configs}
            for name, _ in legs:
                if name not in done:
                    emit({"name": name, "skipped": "sigterm"})
            print("# bench: SIGTERM received; emitting final JSON with "
                  "completed rows", file=sys.stderr, flush=True)

        # Serving acceptance row (ISSUE 7): continuous-batched 64-slot
        # decode vs the one-shot batch-1 path, BOTH measured this run on
        # this box — the ratio the serving layer exists to move (>= 3x is
        # the acceptance bar; batch 64 amortizes the per-step weight
        # streaming that batch-1 decode pays per token).
        s64 = next((c for c in configs
                    if c.get("name") == "gpt2-serve-decode-b64"
                    and "decode_tokens_per_s_per_chip" in c), None)
        o1 = next((c for c in configs
                   if c.get("name") == "gpt2-base-decode-oneshot-b1"
                   and "decode_tokens_per_s_per_chip" in c), None)
        if s64 and o1:
            emit({"name": "serve-vs-oneshot-decode",
                  "serve_b64_tokens_per_s_per_chip":
                      s64["decode_tokens_per_s_per_chip"],
                  "oneshot_b1_tokens_per_s_per_chip":
                      o1["decode_tokens_per_s_per_chip"],
                  "ratio": round(s64["decode_tokens_per_s_per_chip"]
                                 / max(o1["decode_tokens_per_s_per_chip"],
                                       1e-9), 2)})

        # Steady-state A/B delta row: prefetch-off vs prefetch-on at
        # identical settings — the number ISSUE 5 exists to produce. Both
        # sides come from the SAME paired-interleaved leg
        # (measure_prefetch_ab), never from two legs timed minutes apart
        # on a drifting host.
        on = next((c for c in configs
                   if c.get("name") == "diffuseq-base-seq128-prefetch"
                   and "ab_delta_pct" in c), None)
        if on:
            emit({"name": "prefetch-ab-delta",
                  "off_steps_per_s": on["ab_off_steps_per_s"],
                  "on_steps_per_s": on["steps_per_s"],
                  "delta_pct": on["ab_delta_pct"],
                  "method": "paired-interleaved",
                  "rounds": on["ab_rounds"],
                  "window_steps": on["ab_window_steps"],
                  "prefetch_depth": on.get("prefetch_depth"),
                  "dispatch_lag": on.get("dispatch_lag")})

        # Trace-overhead row (ISSUE 12): tracing-off vs tracing-on at
        # identical settings from ONE paired-interleaved leg — the
        # "observability is affordable" acceptance number (|delta| within
        # the box's +-3% noise band).
        tr = next((c for c in configs
                   if c.get("name") == "diffuseq-base-seq128-trace"
                   and "ab_delta_pct" in c), None)
        if tr:
            emit({"name": "trace-ab-delta",
                  "off_steps_per_s": tr["ab_off_steps_per_s"],
                  "on_steps_per_s": tr["steps_per_s"],
                  "delta_pct": tr["ab_delta_pct"],
                  "trace_events": tr["trace_events"],
                  "method": "paired-interleaved",
                  "rounds": tr["ab_rounds"],
                  "window_steps": tr["ab_window_steps"]})

        # ZeRO-1 acceptance row (ISSUE 9): the headline-twin A/B's two
        # numbers in one place — per-replica optimizer-bytes ratio (~dp)
        # and the paired steps/s delta (parity within the noise band).
        z = next((c for c in configs
                  if c.get("name") == "diffuseq-base-seq128-zero1"
                  and "opt_bytes_replica_ratio" in c), None)
        if z:
            emit({"name": "zero1-ab-delta",
                  "off_steps_per_s": z["ab_off_steps_per_s"],
                  "on_steps_per_s": z["steps_per_s"],
                  "delta_pct": z["ab_delta_pct"],
                  "opt_bytes_replica_ratio": z["opt_bytes_replica_ratio"],
                  "dp": z["dp"],
                  "steady_recompile_count": z.get("steady_recompile_count"),
                  "method": "paired-interleaved"})

        # The headline contract holds only for a FULL leg list (legs[0] is
        # the DiffuSeq north star). Under BENCH_ONLY (iteration mode) the
        # first surviving train config — if any — is reported under its own
        # name, never as the north star. In a full run the headline value
        # must come from the headline LEG specifically: if that leg
        # errored, report null (its error row stays in configs) rather
        # than silently promoting the next leg's numbers under the
        # north-star label. (Selection logic lives in final_payload so the
        # watchdog emits the same contract.)
        print_final_once()
    except BenchInterrupted:
        # SIGTERM landed in the post-leg tail (delta-row emit / payload
        # serialization): the rows are complete, so the contract — the
        # final JSON always prints — still holds.
        print_final_once()
    finally:
        # Restored only AFTER the final print: a soft kill in the tail
        # must hit the BenchInterrupted handler above, never the default
        # action (which would end the process with no final JSON).
        signal.signal(signal.SIGTERM, prev_term)


if __name__ == "__main__":
    main()
