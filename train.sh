python3 -m distributed_pipeline_tpu.run.train --distributed --config_json train_config.json
