#!/usr/bin/env bash
# graftlint CI gate, two passes over one analysis engine:
#
#   1. PR annotation pass — `--format github --changed <git diff files>`
#      emits ::error workflow commands ONLY for findings in files this
#      change touches (the analysis itself is still whole-program:
#      cross-module facts need every summary). Skipped when the working
#      tree is clean.
#   2. Whole-program pass — every gated path (the same list the pytest
#      gate in tests/test_graftlint_gate.py uses, imported from it so
#      the two gates can never drift), through the content-hash cache
#      beside the baseline. Fails on any finding outside the committed
#      graftlint_baseline.json.
#
# Exit: 0 clean, 1 findings, 2 usage/setup error.
set -euo pipefail

ROOT="$(cd "$(dirname "${BASH_SOURCE[0]}")/.." && pwd)"
cd "$ROOT"
PY="${PYTHON:-python}"

# the gated path list lives in the pytest gate — single source of truth
mapfile -t GATED < <("$PY" - <<'EOF'
import tests.test_graftlint_gate as gate
print("\n".join(gate.GATED_PATHS))
EOF
)
if [ "${#GATED[@]}" -eq 0 ]; then
    echo "lint_gate: could not load GATED_PATHS" >&2
    exit 2
fi

# pass 1: annotate the changed files (diff against HEAD; in CI, set
# LINT_GATE_DIFF_BASE=origin/main for the PR's merge base)
base="${LINT_GATE_DIFF_BASE:-HEAD}"
mapfile -t CHANGED < <(git diff --name-only "$base" -- '*.py' || true)
if [ "${#CHANGED[@]}" -gt 0 ]; then
    "$PY" -m distributed_pipeline_tpu.analysis \
        --format github --changed "${CHANGED[@]}" -- "${GATED[@]}"
fi

# pass 2: the whole program, warm through the cache
"$PY" -m distributed_pipeline_tpu.analysis -- "${GATED[@]}"
